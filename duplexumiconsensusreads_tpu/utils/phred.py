"""Phred-scale quality math and base-code helpers (NumPy, host-side).

These are the single source of truth for quality<->probability
conversions; the oracle and the JAX kernels both follow the same
conventions (see kernels/consensus.py for the on-device mirror).
"""

from __future__ import annotations

import numpy as np

from duplexumiconsensusreads_tpu.constants import (
    BASE_CHARS,
    CHAR_TO_CODE,
    MAX_PHRED,
    MIN_ERROR_PROB,
)


def phred_to_error(q: np.ndarray) -> np.ndarray:
    """Error probability for integer Phred quality q: e = 10**(-q/10)."""
    return np.maximum(10.0 ** (-np.asarray(q, dtype=np.float64) / 10.0), MIN_ERROR_PROB)


def error_to_phred(e: np.ndarray, max_phred: int = MAX_PHRED) -> np.ndarray:
    """Integer Phred quality for error probability e, clipped to [2, max_phred]."""
    e = np.maximum(np.asarray(e, dtype=np.float64), MIN_ERROR_PROB)
    q = np.floor(-10.0 * np.log10(e) + 1e-9)
    return np.clip(q, 2, max_phred).astype(np.uint8)


def seq_to_codes(seq: str) -> np.ndarray:
    """ACGTN string -> u8 codes (A=0..T=3, N=4)."""
    return np.array([CHAR_TO_CODE.get(c, 4) for c in seq.upper()], dtype=np.uint8)


def codes_to_seq(codes: np.ndarray) -> str:
    """u8 codes -> ACGTN. string (PAD renders as '.')."""
    return "".join(BASE_CHARS[min(int(c), 5)] for c in codes)


def phred_cap_thresholds(max_phred_cap: int) -> np.ndarray:
    """f32 error-rate thresholds 10^(-q/10) for q = 0..max — the ONE
    table both the error-model oracle and device kernel compare
    against; any change here changes both sides together."""
    return (10.0 ** (-np.arange(max_phred_cap + 1) / 10.0)).astype(np.float32)


def phred_cap_from_counts(
    mism: np.ndarray, total: np.ndarray, max_phred_cap: int
) -> np.ndarray:
    """floor(-10*log10((mism+1)/(total+2))) clipped to [2, max], computed
    EXACTLY via f32 threshold comparisons.

    cap = #{q in [0..max] : rate <= 10^(-q/10)} - 1. Both sides of each
    comparison are f32 ((m+1) vs (t+2)*thr[q]); IEEE f32 multiply and
    compare give bit-identical answers on NumPy and XLA/TPU, so the
    device kernel (kernels/error_model.py) reproduces this function
    bit-for-bit — a log10 in f32-on-device vs f64-on-host would flip
    caps at floor boundaries and cascade into second-pass consensus
    differences.
    """
    thr = phred_cap_thresholds(max_phred_cap)
    m = (np.asarray(mism) + 1).astype(np.float32)
    t = (np.asarray(total) + 2).astype(np.float32)
    count = (m[:, None] <= t[:, None] * thr[None, :]).sum(axis=1)
    return np.clip(count - 1, 2, max_phred_cap).astype(np.uint8)


def pack_umi(codes: np.ndarray) -> np.ndarray:
    """Pack 2-bit UMI codes (..., U) into a single int64 per UMI.

    Only valid for U <= 31 and codes in {0..3}; N in a UMI should be
    handled upstream (reads with N UMIs are conventionally dropped).
    For longer UMIs use pack_umi_words64 (multi-word, any length).
    """
    codes = np.asarray(codes, dtype=np.int64)
    u = codes.shape[-1]
    if u > 31:
        raise ValueError(f"UMI length {u} > 31 cannot pack into int64")
    if codes.size and (codes.min() < 0 or codes.max() >= 4):
        raise ValueError(
            "pack_umi requires 2-bit codes in {0..3}; reads with N in the "
            "UMI must be dropped upstream (io layer)"
        )
    shifts = np.arange(u, dtype=np.int64)[::-1] * 2
    return (codes << shifts).sum(axis=-1)


def pack_umi_words64(codes: np.ndarray) -> np.ndarray:
    """Pack 2-bit UMI codes (N, U) into (N, W) big-endian int64 words
    of up to 31 codes each — any UMI length, and comparing the word
    columns lexicographically orders exactly like comparing the code
    strings lexicographically (the invariant every host sort and
    unique-key count relies on).
    """
    codes = np.asarray(codes, dtype=np.int64)
    n, u = codes.shape
    w = max(-(-u // 31), 1)
    padded = np.zeros((n, w * 31), np.int64)
    padded[:, :u] = codes
    shifts = np.arange(31, dtype=np.int64)[::-1] * 2
    return (padded.reshape(n, w, 31) << shifts).sum(axis=-1)


def umi_sort_keys(umi: np.ndarray) -> list[np.ndarray]:
    """np.lexsort key columns for UMI codes, PRIMARY FIRST (callers
    reverse for lexsort's last-key-primary convention)."""
    words = pack_umi_words64(umi)
    return [words[:, i] for i in range(words.shape[1])]
