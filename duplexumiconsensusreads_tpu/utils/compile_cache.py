"""Persistent XLA compilation cache.

First compile of each bucket geometry costs 20-40s on the tunneled
chip; a whole-file + streaming run touches ~5 geometries, so a cold
process spends minutes compiling. jax's persistent compilation cache
amortises that across processes AND across benchmark rounds — the
cache directory lives next to the benchmark input cache.
"""

from __future__ import annotations

import hashlib
import os


def host_cpu_fingerprint() -> str:
    """Short hash of this host's CPU feature flags.

    XLA:CPU AOT artifacts encode the compile machine's feature set; an
    artifact cached on one host and loaded on another can SIGILL
    mid-execution (observed r5: a cache carrying +prefer-no-scatter/
    +prefer-no-gather artifacts segfaulted the bench after the
    benchmark host changed between rounds). Keying the CPU cache
    directory by this fingerprint makes a host change a clean cache
    miss instead of a crash."""
    flags = ""
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 says "flags", aarch64 says "Features"
                if line.startswith(("flags", "Features")):
                    flags = " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    if not flags:
        # parse found nothing (or no /proc): fall back to coarse
        # platform identity rather than letting every such host share
        # sha256("") — which would recreate the stale-artifact collision
        import platform

        flags = "|".join(
            (platform.processor(), platform.machine(), platform.platform())
        )
    return hashlib.sha256(flags.encode()).hexdigest()[:12]


def enable_compile_cache(
    cache_dir: str | None = None, per_host_cpu: bool = False
) -> str | None:
    """Point jax at a persistent compilation cache; best-effort (a
    backend that doesn't support it just keeps compiling).

    per_host_cpu=True suffixes the directory with host_cpu_fingerprint()
    — required for XLA:CPU caches (see that function's rationale);
    TPU-side artifacts key on the accelerator, not the host, so the
    default path stays shared across hosts."""
    import jax

    path = (
        cache_dir
        or os.environ.get("DUT_COMPILE_CACHE")
        or os.path.expanduser("~/.cache/duplexumi/xla")
    )
    if per_host_cpu:
        path = f"{path}-{host_cpu_fingerprint()}"
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return path
    except Exception:
        return None
