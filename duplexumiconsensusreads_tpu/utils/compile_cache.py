"""Persistent XLA compilation cache.

First compile of each bucket geometry costs 20-40s on the tunneled
chip; a whole-file + streaming run touches ~5 geometries, so a cold
process spends minutes compiling. jax's persistent compilation cache
amortises that across processes AND across benchmark rounds — the
cache directory lives next to the benchmark input cache.
"""

from __future__ import annotations

import os


def enable_compile_cache(cache_dir: str | None = None) -> str | None:
    """Point jax at a persistent compilation cache; best-effort (a
    backend that doesn't support it just keeps compiling)."""
    import jax

    path = (
        cache_dir
        or os.environ.get("DUT_COMPILE_CACHE")
        or os.path.expanduser("~/.cache/duplexumi/xla")
    )
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        return path
    except Exception:
        return None
