"""Shared constants: base encoding, Phred conventions, padding sentinels.

Base encoding is 2-bit-friendly: A=0 C=1 G=2 T=3; N=4 carries no
evidence; PAD=5 marks cycles beyond a read's length or slots beyond a
batch's fill. All quality scores are Phred (integer, u8), error
probability e = 10**(-q/10).
"""

BASE_A = 0
BASE_C = 1
BASE_G = 2
BASE_T = 3
BASE_N = 4
BASE_PAD = 5

N_REAL_BASES = 4

BASE_CHARS = "ACGTN."
CHAR_TO_CODE = {c: i for i, c in enumerate(BASE_CHARS)}

# Phred caps. 93 is the largest printable SAM quality ('~' - '!').
MAX_PHRED = 93
NO_CALL_QUAL = 2  # quality emitted for an N consensus call
MIN_ERROR_PROB = 1e-10  # floor when converting quality -> error prob

# Sentinel family/molecule id for reads that belong to no family
# (padding slots, filtered reads).
NO_FAMILY = -1
