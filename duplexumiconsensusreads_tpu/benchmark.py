"""Benchmark harness: reads/sec consensus-called, TPU vs CPU-oracle baseline.

Prints ONE JSON line:
  {"metric": "reads_per_sec_duplex_consensus", "value": N,
   "unit": "reads/s", "vs_baseline": R, ...}

The workload is benchmark config 3/5 (duplex consensus with adjacency
grouping and the per-cycle error model — the hardest fused path) on a
synthetic ctDNA-like batch. No published reference numbers exist
(BASELINE.md): vs_baseline is measured against our own backend="cpu"
NumPy oracle (the stand-in reference implementation, itself a
per-family loop like the reference's pysam path), timed on a subsample
and scaled per-read. Target (BASELINE.json): >=50x.

Beyond the device-compute metric, the line carries an END-TO-END
number (VERDICT r1 item 1): a large coordinate-sorted BAM is simulated
to disk once (cached under .bench_cache/), streamed through the full
`call --chunk-reads` pipeline — native BGZF ingest, bucketing, device
compute, scatter-back, shard write, finalise — and reported as
wall-clock reads/s including ingest + write.

Env knobs: DUT_BENCH_READS (default 600000), DUT_BENCH_CAPACITY (2048),
DUT_BENCH_CPU_SAMPLE (3000), DUT_BENCH_REPS (10),
DUT_BENCH_DRAIN_WORKERS (streaming drain pool size, default 2),
DUT_BENCH_MESH (streaming mesh size for the e2e legs + the K-vs-1
mesh-scaling A/B; 0/unset = executor default; simulate devices on CPU
with XLA_FLAGS=--xla_force_host_platform_device_count=8),
DUT_BENCH_E2E_READS (default 10000000; 0 disables the e2e phase),
DUT_BENCH_E2E_AB (A/B leg size, default 2000000; 0 disables),
DUT_BENCH_AB_BUDGET_S (A/B wall budget the legs shrink to fit, 480),
DUT_BENCH_WIRE_MB (wire probe payload, 32), DUT_BENCH_CPU_E2E_REPS (2),
DUT_BENCH_VEC_REPS (3), DUT_BENCH_CACHE (default .bench_cache),
DUT_BENCH_SERVE_JOBS (serve_n_jobs leg: jobs through the in-process
daemon vs a cold one-shot subprocess, default 3; 0 disables),
DUT_BENCH_SERVE_READS (reads per serve job, default 120000),
DUT_BENCH_SERVE_DAEMONS (serve_fleet leg: in-process daemons sharing
one spool, daemon 0 killed mid-job to measure takeover latency and
per-class queue-wait; default 2, <2 disables),
DUT_BENCH_LIVE_READS (live_follow leg: reads in the paced growing-BAM
follow run, default 120000; 0 disables) and DUT_BENCH_LIVE_SLAB_S (the
synthetic writer's slab cadence, default 0.2),
DUT_BENCH_TRACE (1: every e2e leg records a span capture next to the
cache and the JSON carries per-chunk latency percentiles plus the
byte-ledger wire model — measured floor frac and effective bandwidth;
0 disables),
DUT_BENCH_GATE (1: gate this run's canonical metrics against the
BENCH_r0N trajectory via benchhist.check_regression and exit 1 on a
regression beyond DUT_BENCH_GATE_THRESHOLD, default 0.5; 0 disables).

Stdout contract: the LAST stdout line is the compact canonical JSON
(COMPACT_KEYS — guaranteed to fit the driver's ~2000-byte tail
window), the full result JSON is the line above it and mirrored to
<cache>/bench_full.json.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np


# ONE shared e2e workload definition: both the TPU run (run_e2e) and
# the CPU denominator (run_cpu_e2e) must stream the identical input
# with identical params, or e2e_vs_cpu_e2e compares different work
E2E_CHUNK_READS = 500_000
E2E_MAX_INFLIGHT = 4

# The final stdout line is a COMPACT canonical summary limited to these
# keys. The driver keeps only a ~2000-byte tail of the merged output
# and parses its JSON out of that window; the full result line blew
# past it in r5 ("parsed": null, the trajectory went dark), so the
# contract is now structural: full result on the line above (and in
# <cache>/bench_full.json), canonical metrics — the ones
# tools/bench_history.py tracks — on a last line that always fits.
COMPACT_KEYS = (
    "metric", "value", "unit", "vs_baseline", "tflops", "mfu",
    "vs_vectorized_cpu", "ssc_method",
    "e2e_reads_per_sec", "e2e_wall_s",
    "e2e_mfu", "e2e_roofline_frac",
    "e2e_wire_floor_frac", "e2e_wire_floor_frac_measured",
    "e2e_wire_h2d_mb_s_measured", "e2e_wire_d2h_mb_s_measured",
    "e2e_bytes_per_read", "e2e_packed_speedup", "e2e_d2h_packed_speedup",
    "e2e_h2d_bits_per_cycle", "e2e_prefetch_depth", "e2e_ingest_overlap",
    "e2e_fill_factor", "tuner_predicted_speedup", "e2e_vs_cpu_e2e",
    "e2e_mesh_devices", "e2e_mesh_scaling",
    "serve_amortised_speedup", "serve_fleet_takeover_latency_s",
    "serve_quarantine_after_crashes", "serve_watchdog_detect_latency_s",
    "serve_shard_speedup", "serve_shard_merge_s",
    "serve_xhost_takeover_latency_s", "serve_xhost_recovered",
    "fleet_e2e_p95_s", "fleet_takeover_gap_s",
    "live_first_snapshot_latency_s", "live_steady_lag_chunks",
)


def compact_result(result: dict, full_path: str | None = None) -> dict:
    """The last-stdout-line summary: COMPACT_KEYS present in
    ``result``, plus a pointer at the mirrored full JSON."""
    out = {k: result[k] for k in COMPACT_KEYS if k in result}
    if full_path:
        out["full"] = full_path
    return out


def run_bench_gate(result: dict) -> tuple[bool, list[str]]:
    """``bench_history.py --check`` wired into the bench leg: this
    run's canonical metrics as the candidate round against the
    driver's recorded BENCH_r0N trajectory (beside the repo root /
    cwd). No trajectory -> vacuously OK (fresh checkouts, tests).
    DUT_BENCH_GATE=0 skips, DUT_BENCH_GATE_THRESHOLD overrides the
    loose 50% default (the tunnel wire varies ~3x intra-day; the gate
    is for metrics halving or vanishing, not weather)."""
    from duplexumiconsensusreads_tpu import benchhist

    paths = benchhist.default_paths(".")
    if not paths:
        return True, []
    try:
        rounds = [benchhist.load_round(p) for p in paths]
    except (OSError, ValueError) as e:
        return True, [f"gate skipped: unreadable trajectory ({e})"]
    rounds.append({
        "name": "current", "path": "<this run>",
        "metrics": dict(result), "salvaged": False, "rc": None,
    })
    threshold = float(os.environ.get("DUT_BENCH_GATE_THRESHOLD", 0.5))
    return benchhist.check_regression(rounds, threshold=threshold)


def wire_probe(mb: int | None = None) -> dict:
    """Measure the raw host<->device wire, both directions, with a
    ~mb-MB uint8 payload. On a tunneled chip the wire varies ~3x
    intra-day (r4: same-day e2e runs spanned 9.4-31.0k reads/s with no
    code change); emitting the measured bandwidth beside every e2e
    capture turns "tunnel weather" from an assertion into a per-capture
    fact, and bytes/bandwidth gives an arithmetic floor for the e2e
    wall (VERDICT r4 item 1a). The device->host fetch of a 1-element
    slice is the true h2d barrier — block_until_ready returns early on
    tunneled platforms (measured r3)."""
    import jax

    if mb is None:
        mb = int(os.environ.get("DUT_BENCH_WIRE_MB", 32))
    dev = jax.devices()[0]
    payload = np.random.default_rng(0).integers(
        0, 256, size=(mb << 20,), dtype=np.uint8
    )
    # warm the FULL-SHAPE path untimed: the [:1] barrier below is a
    # jit-compiled slice keyed on the payload shape, and a cold compile
    # (seconds over the tunnel) would land inside the first probe's
    # timing only — systematically skewing the before/after bracket
    # this probe exists to make trustworthy (review r5 finding)
    warm = jax.device_put(payload, dev)
    np.asarray(warm[:1])
    warm.delete()
    t0 = time.monotonic()
    x = jax.device_put(payload, dev)
    np.asarray(x[:1])  # true completion barrier (1-elem fetch)
    h2d_s = time.monotonic() - t0
    t0 = time.monotonic()
    back = np.asarray(x)
    d2h_s = time.monotonic() - t0
    assert back[-1] == payload[-1]
    x.delete()
    # decimal MB/s: the e2e byte counters report bytes/1e6, and the
    # floor arithmetic divides one by the other — mixing MiB into the
    # bandwidth side would bias every floor ~4.6% low (review r5)
    dec_mb = (mb << 20) / 1e6
    return {
        "wire_mb": mb,
        "wire_h2d_mb_s": round(dec_mb / max(h2d_s, 1e-9), 1),
        "wire_d2h_mb_s": round(dec_mb / max(d2h_s, 1e-9), 1),
    }


def _e2e_params():
    from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex", error_model="cycle", min_duplex_reads=1)
    return gp, cp


def _e2e_input(n_target: int) -> tuple[str, float]:
    """Simulate-or-reuse the cached coordinate-sorted input BAM for an
    ~n_target-read e2e run. Returns (path, sim_seconds). The cache key
    covers the FULL workload definition, so editing the config can
    never silently reuse a stale input BAM."""
    import dataclasses as _dc
    import hashlib as _hl

    from duplexumiconsensusreads_tpu.simulate import SimConfig
    from duplexumiconsensusreads_tpu.simulate.bigsim import simulate_bam_file

    cache = os.environ.get("DUT_BENCH_CACHE", ".bench_cache")
    os.makedirs(cache, exist_ok=True)
    n_mol = n_target // 8  # ~8 reads/molecule with the config below
    cfg = SimConfig(
        read_len=150,
        n_positions=1000,
        mean_family_size=4,
        umi_error=0.01,
        duplex=True,
    )
    tag = _hl.sha256(
        json.dumps([_dc.asdict(cfg), n_mol, 7], sort_keys=True).encode()
    ).hexdigest()[:10]
    in_path = os.path.join(cache, f"e2e_{tag}.bam")
    sim_s = 0.0
    if not os.path.exists(in_path):
        res = simulate_bam_file(
            in_path + ".tmp", n_mol, cfg=cfg, chunk_molecules=25_000, seed=7
        )
        os.replace(in_path + ".tmp", in_path)
        sim_s = res["seconds"]
    return in_path, sim_s


def run_e2e(
    n_target: int, packed: str = "auto", prefix: str = "e2e",
    d2h_packed: str = "auto", n_devices: int | None = None,
    ingest_overlap: str = "auto",
) -> dict:
    """Stream a cached large simulated BAM through the full pipeline;
    return wall-clock metrics including ingest and write. packed="off"
    disables the H2D wire packing and d2h_packed="off" the packed
    consensus-only return path — the same-run A/B legs the driver
    captures (VERDICT r3 item 5: a README-only A/B is not evidence).

    Every leg records a span capture (DUT_BENCH_TRACE=0 disables) and
    the JSON carries the per-chunk latency percentiles from it — the
    e2e wall decomposed into the numbers a serving SLO is written
    against. The capture file stays in the cache dir for post-mortem
    (`tools/trace_report.py <cache>/e2e_trace.jsonl`)."""
    from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus

    cache = os.environ.get("DUT_BENCH_CACHE", ".bench_cache")
    in_path, sim_s = _e2e_input(n_target)
    out_path = os.path.join(cache, "e2e_out.bam")
    trace_path = None
    if int(os.environ.get("DUT_BENCH_TRACE", 1)):
        trace_path = os.path.join(cache, f"{prefix}_trace.jsonl")
    gp, cp = _e2e_params()
    prefetch_depth = int(os.environ.get("DUT_BENCH_PREFETCH_DEPTH", 2))
    # mesh size for this leg: the explicit kwarg (the scaling A/B), or
    # DUT_BENCH_MESH (0/unset = the executor default, all local
    # devices — on CPU simulate a mesh with
    # XLA_FLAGS=--xla_force_host_platform_device_count=8)
    if n_devices is None:
        n_devices = int(os.environ.get("DUT_BENCH_MESH", 0)) or None
    t0 = time.monotonic()
    rep = stream_call_consensus(
        in_path,
        out_path,
        gp,
        cp,
        n_devices=n_devices,
        capacity=int(os.environ.get("DUT_BENCH_CAPACITY", 2048)),
        chunk_reads=E2E_CHUNK_READS,
        max_inflight=E2E_MAX_INFLIGHT,
        drain_workers=int(os.environ.get("DUT_BENCH_DRAIN_WORKERS", 2)),
        packed=packed,
        d2h_packed=d2h_packed,
        prefetch_depth=prefetch_depth,
        ingest_overlap=ingest_overlap,
        trace_path=trace_path,
    )
    wall = time.monotonic() - t0
    try:
        os.remove(out_path)
    except OSError:
        pass
    from duplexumiconsensusreads_tpu.runtime.executor import default_ssc_method

    extra = {}
    if trace_path:
        from duplexumiconsensusreads_tpu.telemetry import ledger as trace_ledger
        from duplexumiconsensusreads_tpu.telemetry import report as trace_report

        try:
            records = trace_report.load_trace(trace_path)
            pct = trace_report.chunk_latency_percentiles(records)
            extra = {
                f"{prefix}_chunk_p50_s": pct["p50_s"],
                f"{prefix}_chunk_p95_s": pct["p95_s"],
                f"{prefix}_chunk_max_s": pct["max_s"],
                f"{prefix}_chunk_dominant": pct["dominant_stages"],
                f"{prefix}_trace": trace_path,
            }
            # the MEASURED wire model (byte ledger): floor fraction and
            # effective bandwidth from the run's own transfer spans —
            # no probe bracket, no weather mismatch. The probe-derived
            # e2e_wire_floor_frac stays beside it for continuity.
            fl = trace_ledger.wire_floor(records)
            bw = trace_ledger.bandwidth_stats(records)
            extra[f"{prefix}_wire_floor_frac_measured"] = fl["frac"]
            if "h2d" in bw:
                extra[f"{prefix}_wire_h2d_mb_s_measured"] = (
                    bw["h2d"]["effective_mb_s"]
                )
            if "d2h" in bw:
                extra[f"{prefix}_wire_d2h_mb_s_measured"] = (
                    bw["d2h"]["effective_mb_s"]
                )
            # the H2D rung the run actually used, from the ledger's
            # per-dispatch bpc attrs (modal across fresh chunks): 16 =
            # unpacked, 8 = byte, 7/5 = the sub-byte qual-dictionary
            # rungs
            bpcs = [
                r["bpc"] for r in trace_ledger.xfer_records(records)
                if r.get("dir") == "h2d" and "bpc" in r
            ]
            if bpcs:
                extra[f"{prefix}_h2d_bits_per_cycle"] = max(
                    set(bpcs), key=bpcs.count
                )
            pk = trace_ledger.packing_stats(records)
            if "d2h_packing_ratio" in pk:
                extra[f"{prefix}_d2h_packing_ratio"] = pk["d2h_packing_ratio"]
            # the device ledger: honest MFU and roofline position
            # MEASURED from the capture's own dev records — the e2e
            # twin of the compute bench's analytic MFU (absent on
            # pre-devledger captures)
            from duplexumiconsensusreads_tpu.telemetry import devledger

            dtot = devledger.device_totals(records)
            if dtot:
                extra[f"{prefix}_mfu"] = dtot["mfu"]
                extra[f"{prefix}_device_gflops"] = round(
                    dtot["flops"] / 1e9, 3
                )
                roofl = devledger.roofline(records)
                if roofl:
                    extra[f"{prefix}_roofline_frac"] = (
                        roofl["attainable_frac"]
                    )
            comp = devledger.compile_stats(records)
            if comp:
                extra[f"{prefix}_compile_s"] = comp["compile_s"]
        except (OSError, ValueError) as e:
            # telemetry must never sink the bench capture itself
            extra = {f"{prefix}_trace_error": str(e)[:200]}
    if prefix == "e2e":
        # satellite of the canonical capture: the busy-vs-wall table in
        # the human journal, previously only reachable via
        # `tools/profile_phases.py --report` on a saved report JSON
        from duplexumiconsensusreads_tpu.runtime.executor import busy_wall_table

        lines, bugs = busy_wall_table(
            rep.seconds, drain_workers=max(rep.n_drain_workers, 1)
        )
        print("# e2e busy-vs-wall (per-stage busy seconds, overlapped):",
              file=sys.stderr)
        for ln in lines:
            print(f"#   {ln}", file=sys.stderr)
        if bugs:
            print(f"#   ACCOUNTING BUG in stages: {', '.join(bugs)}",
                  file=sys.stderr)
        sys.stderr.flush()

    return {
        **extra,
        f"{prefix}_reads": rep.n_records,
        f"{prefix}_wall_s": round(wall, 2),
        f"{prefix}_reads_per_sec": round(rep.n_records / wall, 1),
        f"{prefix}_consensus": rep.n_consensus,
        f"{prefix}_sim_s": round(sim_s, 1),
        f"{prefix}_input_mb": round(os.path.getsize(in_path) / 1e6, 1),
        # the streaming executor picks its own backend default —
        # DUT_SSC_METHOD only steers the compute phase, and the JSON
        # must not attribute e2e numbers to the wrong kernel
        f"{prefix}_ssc_method": default_ssc_method(),
        # measured wire payload of this run (device inputs dispatched /
        # outputs materialised) — divides against the wire probe's MB/s
        # for the arithmetic wall floor
        f"{prefix}_h2d_mb": round(rep.bytes_h2d / 1e6, 1),
        f"{prefix}_d2h_mb": round(rep.bytes_d2h / 1e6, 1),
        # total wire traffic per read processed: the canonical "did a
        # faster run actually move fewer bytes" number the trajectory
        # (tools/bench_history.py) tracks across rounds
        f"{prefix}_bytes_per_read": round(
            (rep.bytes_h2d + rep.bytes_d2h) / max(rep.n_records, 1), 1
        ),
        # per-phase BUSY-time breakdown (VERDICT r2 item 2). Since the
        # pipelined drain, stages overlap: the dict carries per-stage
        # busy seconds plus main_loop_stall / drain_utilization, which
        # are the honest wall-side views (a stage's busy time no longer
        # bounds the wall it cost the run)
        f"{prefix}_phases": {k: v for k, v in rep.seconds.items() if k != "total"},
        f"{prefix}_drain_workers": rep.n_drain_workers,
        f"{prefix}_prefetch_depth": prefetch_depth,
        # the leg's resolved mesh (devices the bucket batch sharded
        # over) and the padding it cost — the scaling leg's context
        f"{prefix}_mesh_devices": rep.n_devices,
        f"{prefix}_mesh_pad_buckets": rep.n_mesh_pad_buckets,
    }


def run_per_config(mesh) -> dict:
    """Device-compute reads/s for EACH named BASELINE.json config on an
    apt sim geometry (amplicon / panel / ctDNA / exome-sharded /
    low-VAF), so a regression in any single path — e.g. the exact-match
    fast path — is driver-visible, not hidden inside the composite
    headline (VERDICT r3 item 4). Same methodology as the headline
    compute phase: device-resident inputs, async reps, one final fetch
    as the barrier. Config 4's distinguishing axis on a single chip is
    its jumbo capacity (the mesh sharding itself is exercised by the
    driver's multichip dryrun)."""
    import jax

    from duplexumiconsensusreads_tpu.bucketing import build_buckets, stack_buckets
    from duplexumiconsensusreads_tpu.parallel.sharded import (
        presharded_pipeline,
        shard_stacked,
    )
    from duplexumiconsensusreads_tpu.runtime.executor import partition_buckets
    from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
    from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

    n_target = int(os.environ.get("DUT_BENCH_CONFIG_READS", 200_000))
    reps = int(os.environ.get("DUT_BENCH_CONFIG_REPS", 6))
    n_dev = len(jax.devices())
    adj = dict(strategy="adjacency")
    plans = {
        # amplicon: few deep positions, exact grouping, single strand
        "config1": (
            dict(read_len=150, n_positions=24, mean_family_size=6,
                 duplex=False, seed=11),
            GroupingParams(strategy="exact"),
            ConsensusParams(mode="single_strand"),
            2048,
        ),
        # hybrid-capture panel: UMI errors, directional adjacency
        "config2": (
            dict(read_len=150, n_positions=400, mean_family_size=5,
                 umi_error=0.02, duplex=False, seed=12),
            GroupingParams(**adj),
            ConsensusParams(mode="single_strand"),
            2048,
        ),
        # ctDNA panel: duplex reconciliation
        "config3": (
            dict(read_len=150, n_positions=450, mean_family_size=4,
                 umi_error=0.01, duplex=True, seed=13),
            GroupingParams(paired=True, **adj),
            ConsensusParams(mode="duplex"),
            2048,
        ),
        # whole-exome sharded: sparse positions, jumbo capacity
        "config4": (
            dict(read_len=150, n_positions=1600, mean_family_size=3,
                 umi_error=0.01, duplex=True, seed=14),
            GroupingParams(paired=True, **adj),
            ConsensusParams(mode="duplex"),
            4096,
        ),
        # low-VAF calling: duplex + per-cycle error model
        "config5": (
            dict(read_len=150, n_positions=450, mean_family_size=4,
                 umi_error=0.01, cycle_error_slope=0.002, duplex=True,
                 seed=15),
            GroupingParams(paired=True, **adj),
            ConsensusParams(mode="duplex", error_model="cycle"),
            2048,
        ),
    }
    out = {}
    for name, (sim_kw, gp, cp, capacity) in plans.items():
        per_mol = sim_kw["mean_family_size"] * (2 if sim_kw["duplex"] else 1)
        batch, _ = simulate_batch(
            SimConfig(n_molecules=max(64, n_target // per_mol), **sim_kw)
        )
        n_reads = int(np.asarray(batch.valid).sum())
        buckets = build_buckets(batch, capacity=capacity, grouping=gp)
        classes = []
        for cbuckets, cspec in partition_buckets(buckets, gp, cp):
            stacked = stack_buckets(cbuckets, multiple_of=n_dev)
            classes.append((cspec, shard_stacked(stacked, mesh)))
        jax.block_until_ready([c[1] for c in classes])

        def run_all():
            return [presharded_pipeline(args, cspec, mesh) for cspec, args in classes]

        for o in run_all():
            np.asarray(o["n_families"])  # compile + true barrier
        # best of two timing rounds: the r4 canonical capture recorded
        # config4 at 86.5 ms/step where clean same-process re-measures
        # give 68-72 ms — single-round timings right after a burst of
        # fresh compiles + host work absorb one-off stalls (compile
        # thread tails, allocator warmup, tunnel hiccups) that a second
        # round never shows. Best-of mirrors the CPU-denominator
        # discipline: the honest steady-state number for both sides.
        dt = None
        for _ in range(2):
            t0 = time.monotonic()
            outs = [run_all() for _ in range(reps)]
            np.asarray(outs[-1][-1]["n_families"])
            d = (time.monotonic() - t0) / reps
            dt = d if dt is None else min(dt, d)
        out[name] = {
            "reads_per_sec": round(n_reads / dt, 1),
            "n_reads": n_reads,
            "capacity": capacity,
            "step_s": round(dt, 4),
        }
    return out


def run_serve_bench(n_jobs: int) -> dict:
    """The ``serve_n_jobs`` leg: N identical small jobs through an
    in-process daemon vs the same job ONE-SHOT in a fresh process.

    The one-shot subprocess deliberately gets a throwaway compile-cache
    dir: it pays the full per-process XLA compile + device warm-up toll
    (~11.6s on the r05 capture) that every ``call`` invocation pays
    without the service. The daemon jobs run on the warm process, so
    per-job wall vs one-shot wall IS the compile amortisation, measured
    — emitted into the BENCH JSON as serve_* keys. Per-job walls come
    from the service capture's job_completed events (completion order).
    """
    import shutil
    import subprocess
    import sys as _sys
    import tempfile

    from duplexumiconsensusreads_tpu.serve import ConsensusService, client
    from duplexumiconsensusreads_tpu.telemetry import report as trace_report

    cache = os.environ.get("DUT_BENCH_CACHE", ".bench_cache")
    n_reads = int(os.environ.get("DUT_BENCH_SERVE_READS", 120_000))
    in_path, _ = _e2e_input(n_reads)
    config = dict(
        grouping="adjacency", mode="duplex", error_model="cycle",
        capacity=int(os.environ.get("DUT_BENCH_CAPACITY", 2048)),
        chunk_reads=max(n_reads // 4, 10_000),
    )
    out_cold = os.path.join(cache, "serve_cold.bam")
    spec_json = json.dumps({
        "job_id": "job-bench-cold", "input": os.path.abspath(in_path),
        "output": os.path.abspath(out_cold), "config": config,
    })
    child = f"""
import json, tempfile, time
from duplexumiconsensusreads_tpu.utils.compile_cache import enable_compile_cache
enable_compile_cache(tempfile.mkdtemp(prefix="serve_cold_xla"), per_host_cpu=True)
from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus
from duplexumiconsensusreads_tpu.serve.job import (
    job_params, serve_provenance, validate_spec,
)
spec = validate_spec(json.loads({spec_json!r}))
gp, cp, kw = job_params(spec)
t0 = time.monotonic()
rep = stream_call_consensus(
    spec.input, spec.output, gp, cp,
    provenance_cl=serve_provenance(spec.config), **kw,
)
print(json.dumps({{"wall": time.monotonic() - t0, "reads": rep.n_records}}))
"""
    env = dict(os.environ)
    env.pop("DUT_COMPILE_CACHE", None)  # the cold leg must really be cold
    out: dict = {"serve_n_jobs": n_jobs, "serve_reads_per_job": n_reads}
    proc = subprocess.run(
        [_sys.executable, "-c", child], capture_output=True, text=True, env=env,
    )
    try:
        os.remove(out_cold)
    except OSError:
        pass
    if proc.returncode != 0:
        sys.stderr.write(proc.stderr[-2000:])
        return {**out, "serve_error": f"cold one-shot exit {proc.returncode}"}
    cold = json.loads(proc.stdout.strip().splitlines()[-1])
    out["serve_oneshot_cold_wall_s"] = round(cold["wall"], 2)

    spool = os.path.join(cache, "serve_spool")
    shutil.rmtree(spool, ignore_errors=True)
    trace_path = os.path.join(cache, "serve_trace.jsonl")
    outs = [os.path.join(cache, f"serve_out{i}.bam") for i in range(n_jobs)]
    for o in outs:
        client.submit(spool, in_path, o, config=config)
    t0 = time.monotonic()
    snap = ConsensusService(
        spool, chunk_budget=0, trace_path=trace_path
    ).run_until_idle()
    serve_wall = time.monotonic() - t0
    for o in outs:
        try:
            os.remove(o)
        except OSError:
            pass
    if snap["jobs_done"] != n_jobs:
        return {**out, "serve_error": f"daemon finished {snap['jobs_done']}/"
                f"{n_jobs} jobs"}
    records = trace_report.load_trace(trace_path)
    walls = [
        float(r["wall_s"]) for r in records
        if r.get("type") == "event" and r.get("name") == "job_completed"
    ]
    out.update({
        "serve_wall_s": round(serve_wall, 2),
        "serve_job_walls_s": [round(w, 2) for w in walls],
        "serve_compile_hit_rate": snap["compile_hit_rate"],
        # the headline: what one job costs a cold process vs the warm
        # daemon — the measured value of keeping the device/compiles hot
        "serve_amortised_speedup": round(
            cold["wall"] / max(min(walls), 1e-9), 2
        ) if walls else 0.0,
        "serve_trace": trace_path,
    })
    return out


def run_live_follow_bench() -> dict:
    """The ``live_follow`` leg (informational, non-gating): the
    streaming executor tailing a BAM while a paced writer is still
    appending it — the `sequencer-is-running` serving shape the live/
    subsystem exists for.

    Two canonical numbers:

    - ``live_first_snapshot_latency_s``: wall from follower start to
      the first published indexed snapshot — how long before a
      downstream consumer can open SOMETHING valid;
    - ``live_steady_lag_chunks``: mean follower lag behind the writer
      over the second half of the run, in committed-chunk units — the
      steady-state distance between the instrument and the consensus.

    Non-gating on purpose: both numbers are paced by the synthetic
    writer's slab cadence (DUT_BENCH_LIVE_SLAB_S), not by the pipeline
    alone, so they are a serving-shape observation, not a regression
    oracle. DUT_BENCH_LIVE_READS=0 disables the leg."""
    import threading

    from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus

    cache = os.environ.get("DUT_BENCH_CACHE", ".bench_cache")
    n_reads = int(os.environ.get("DUT_BENCH_LIVE_READS", 120_000))
    src_path, _ = _e2e_input(n_reads)
    with open(src_path, "rb") as f:
        raw = f.read()
    in_path = os.path.join(cache, "live_in.bam")
    out_path = os.path.join(cache, "live_out.bam")
    trace_path = None
    if int(os.environ.get("DUT_BENCH_TRACE", 1)):
        trace_path = os.path.join(cache, "live_trace.jsonl")
    gp, cp = _e2e_params()
    chunk_reads = max(n_reads // 8, 10_000)
    n_slabs = 20
    slab_s = float(os.environ.get("DUT_BENCH_LIVE_SLAB_S", 0.2))
    slab = max(1, (len(raw) + n_slabs - 1) // n_slabs)
    written = {"bytes": 0}

    def writer():
        with open(in_path, "wb") as f:
            for off in range(0, len(raw), slab):
                f.write(raw[off:off + slab])
                f.flush()
                written["bytes"] = off + len(raw[off:off + slab])
                time.sleep(slab_s)

    with open(in_path, "wb"):
        pass  # the follower may open before the first slab lands
    commits: list = []  # (chunks_done, writer_frac, t_since_start)
    first_snap = [0.0]
    t0 = time.monotonic()

    def progress(_k, _rep):
        now = time.monotonic() - t0
        if _rep.snapshot_seq >= 1 and not first_snap[0]:
            first_snap[0] = now
        commits.append((len(commits) + 1, written["bytes"] / len(raw), now))

    wt = threading.Thread(target=writer)
    wt.start()
    try:
        rep = stream_call_consensus(
            in_path, out_path, gp, cp,
            capacity=int(os.environ.get("DUT_BENCH_CAPACITY", 2048)),
            chunk_reads=chunk_reads,
            follow=True, live_poll_s=0.05, snapshot_chunks=1,
            progress=progress, trace_path=trace_path,
        )
    finally:
        wt.join()
    wall = time.monotonic() - t0
    for p in (out_path, in_path):
        try:
            os.remove(p)
        except OSError:
            pass
    # lag in chunk units at each commit: where the writer was (as a
    # fraction of the final chunk grid) minus where the follower was —
    # averaged over the run's second half, after the warm-up commits
    lags = [
        max(frac * rep.n_chunks - done, 0.0)
        for done, frac, _ in commits[len(commits) // 2:]
    ]
    out = {
        "live_follow_reads": int(rep.n_records),
        "live_follow_wall_s": round(wall, 2),
        "live_follow_chunks": int(rep.n_chunks),
        "live_snapshots_published": int(rep.snapshot_seq),
        "live_first_snapshot_latency_s": round(first_snap[0], 3),
        "live_steady_lag_chunks": round(
            sum(lags) / len(lags), 3
        ) if lags else 0.0,
        # the follower's own idle accounting, from the phase ledger
        "live_poll_s": round(rep.seconds.get("live_poll", 0.0), 2),
        "live_wait_s": round(rep.seconds.get("live_wait", 0.0), 2),
    }
    if trace_path:
        out["live_trace"] = trace_path
    return out


def run_serve_fleet_bench(n_daemons: int) -> dict:
    """The ``serve_fleet`` leg: jobs submitted through ``n_daemons``
    in-process daemons sharing ONE spool, exercising the lease/claim
    protocol end to end under load — then daemon 0 is killed mid-job
    (InjectedKill from its own slice, the modelled SIGKILL) and the
    survivors take its lease over.

    Emits into the BENCH JSON:
      serve_fleet_takeover_latency_s  wall from the victim's death to
                                      its job running again elsewhere
                                      (dead-owner detection + claim)
      serve_fleet_class_queue_wait    per-priority-class queue-wait
                                      p50/p95 from metrics.json — the
                                      admission-control SLO surface
    """
    import shutil
    import threading

    from duplexumiconsensusreads_tpu.runtime import faults
    from duplexumiconsensusreads_tpu.serve import ConsensusService, client
    from duplexumiconsensusreads_tpu.serve.queue import SpoolQueue

    cache = os.environ.get("DUT_BENCH_CACHE", ".bench_cache")
    n_reads = int(os.environ.get("DUT_BENCH_SERVE_READS", 120_000))
    in_path, _ = _e2e_input(n_reads)
    config = dict(
        grouping="adjacency", mode="duplex", error_model="cycle",
        capacity=int(os.environ.get("DUT_BENCH_CAPACITY", 2048)),
        chunk_reads=max(n_reads // 4, 10_000),
    )
    spool = os.path.join(cache, "serve_fleet_spool")
    shutil.rmtree(spool, ignore_errors=True)
    n_jobs = max(3, n_daemons + 1)
    outs = [os.path.join(cache, f"serve_fleet_out{i}.bam") for i in range(n_jobs)]
    jids = [
        # one urgent job in the mix so the per-class latency table has
        # two rows; the rest ride the default class
        client.submit(spool, in_path, o, config=config,
                      priority=(0 if i == n_jobs - 1 else 1))
        for i, o in enumerate(outs)
    ]
    out: dict = {"serve_fleet_daemons": n_daemons, "serve_fleet_jobs": n_jobs}

    victim = ConsensusService(
        spool, chunk_budget=0, poll_s=0.02, lease_s=5.0,
        daemon_id="fleet-victim",
        trace_path=os.path.join(spool, "service.fleet-victim.trace.jsonl"),
    )
    orig_run_slice = victim.worker.run_slice

    def dying_run_slice(spec, budget, should_yield, drain_event, lease=None):
        # one fresh chunk commits, then the budget check consults
        # should_yield — which kills the daemon exactly as a SIGKILL
        # mid-slice would, lease still held
        def die():
            raise faults.InjectedKill("serve_fleet: victim daemon killed")

        return orig_run_slice(spec, 1, die, drain_event, lease=lease)

    victim.worker.run_slice = dying_run_slice
    t_dead = [0.0]

    def run_victim():
        try:
            victim.run_until_idle()
        except BaseException:  # noqa: BLE001 — the injected death
            t_dead[0] = time.monotonic()

    vt = threading.Thread(target=run_victim, daemon=True)
    vt.start()
    vt.join(timeout=600)
    if vt.is_alive() or not t_dead[0]:
        return {**out, "serve_fleet_error": "victim did not die on schedule"}
    q = SpoolQueue(spool)
    q.refresh()
    victim_jobs = [
        jid for jid, e in q.jobs.items() if e.get("state") == "running"
    ]
    if not victim_jobs:
        return {**out, "serve_fleet_error": "victim died holding no lease"}

    t0 = time.monotonic()
    survivors = [
        ConsensusService(
            spool, chunk_budget=0, poll_s=0.02, lease_s=5.0,
            daemon_id=f"fleet-survivor-{i}",
            trace_path=os.path.join(
                spool, f"service.fleet-survivor-{i}.trace.jsonl"
            ),
        )
        for i in range(1, n_daemons)
    ]
    sthreads = [
        threading.Thread(target=s.run_until_idle, daemon=True)
        for s in survivors
    ]
    for t in sthreads:
        t.start()
    # takeover latency: victim death -> its job running under a new
    # lease (dead-owner detection through the in-process registry, then
    # a fresh claim)
    takeover = None
    deadline = time.monotonic() + 300
    jid0 = victim_jobs[0]
    while time.monotonic() < deadline:
        q.refresh()
        e = q.jobs.get(jid0, {})
        if e.get("state") == "done" or (
            e.get("state") == "running"
            and (e.get("lease") or {}).get("owner") != "fleet-victim"
        ):
            takeover = time.monotonic() - t_dead[0]
            break
        time.sleep(0.005)
    for t in sthreads:
        t.join(timeout=600)
    fleet_wall = time.monotonic() - t0
    q.refresh()
    n_done = sum(1 for e in q.jobs.values() if e.get("state") == "done")
    for o in outs:
        try:
            os.remove(o)
        except OSError:
            pass
    if n_done != n_jobs:
        return {**out, "serve_fleet_error":
                f"fleet finished {n_done}/{n_jobs} jobs"}
    out.update({
        "serve_fleet_wall_s": round(fleet_wall, 2),
        "serve_fleet_takeover_latency_s": (
            round(takeover, 3) if takeover is not None else None
        ),
        "serve_fleet_recovered": sum(
            s.counters["jobs_recovered"] for s in survivors
        ),
    })
    try:
        with open(os.path.join(spool, "metrics.json")) as f:
            metrics = json.load(f)
        lat = metrics.get("class_latency", {})
        out["serve_fleet_class_queue_wait"] = {
            pri: {
                "p50_s": row.get("queue_wait_p50_s"),
                "p95_s": row.get("queue_wait_p95_s"),
                "n": row.get("n_queue_wait"),
            }
            for pri, row in lat.items()
        }
    except (OSError, ValueError):
        pass  # metrics snapshot is best-effort observability
    # the leg measures its OWN observability layer: stitch the victim's
    # (unclean, SIGKILL-modelled) and the survivors' captures plus the
    # journal into cross-daemon timelines and report the fleet-level
    # e2e p95 and the takeover recovery gap — the same numbers
    # tools/fleet_report.py would print for this spool, and a CPU
    # sanity check that the stitcher's sum-check stays green under a
    # real takeover (a FAILED stitch is worth seeing in the trajectory:
    # the key goes absent and bench_history flags the hole)
    try:
        from duplexumiconsensusreads_tpu.telemetry import fleet

        caps = fleet.load_captures(fleet.discover_service_captures(spool))
        stitched = fleet.stitch(
            caps, journal=fleet.load_journal(os.path.join(spool, "queue.json"))
        )
        fm = fleet.fleet_metrics(
            stitched, metrics_docs=fleet.load_metrics_docs(spool)
        )
        out["serve_fleet_stitch_ok"] = stitched["ok"]
        if stitched["ok"]:
            if isinstance(fm.get("e2e_p95_s"), (int, float)):
                out["fleet_e2e_p95_s"] = round(fm["e2e_p95_s"], 3)
            if isinstance(fm.get("takeover_gap_max_s"), (int, float)):
                out["fleet_takeover_gap_s"] = round(
                    fm["takeover_gap_max_s"], 3
                )
        else:
            out["serve_fleet_stitch_problems"] = stitched["problems"][:5]
    except Exception as e:  # noqa: BLE001 — the bench must still report
        out["serve_fleet_stitch_error"] = repr(e)[:200]
    return out


def run_serve_xhost_bench() -> dict:
    """The ``serve_xhost`` sub-leg: the serve_fleet takeover scenario
    re-run CROSS-HOST — two synthetic hosts on one sharedfs-store
    spool (distinct host ids, ±1h monotonic epoch skews the probe
    calibration must cancel), host A killed mid-slice. Detection is
    translated lease expiry — never a pid probe — so the latency is
    lease_s-dominated by design; the number characterises the pid-free
    takeover path, not throughput (informational, non-gating).

      serve_xhost_takeover_latency_s  victim death -> its job running
                                      (or done) under host B's lease
      serve_xhost_recovered           takeovers host B journaled
    """
    import shutil
    import threading

    from duplexumiconsensusreads_tpu.runtime import faults
    from duplexumiconsensusreads_tpu.serve import ConsensusService, client
    from duplexumiconsensusreads_tpu.serve.queue import SpoolQueue
    from duplexumiconsensusreads_tpu.serve.store import resolve_store

    cache = os.environ.get("DUT_BENCH_CACHE", ".bench_cache")
    n_reads = int(os.environ.get("DUT_BENCH_SERVE_READS", 120_000))
    in_path, _ = _e2e_input(n_reads)
    config = dict(
        grouping="adjacency", mode="duplex", error_model="cycle",
        capacity=int(os.environ.get("DUT_BENCH_CAPACITY", 2048)),
        chunk_reads=max(n_reads // 4, 10_000),
    )
    spool = os.path.join(cache, "serve_xhost_spool")
    shutil.rmtree(spool, ignore_errors=True)
    lease_s = 2.0
    store_a = resolve_store(spool, "sharedfs", pin=True,
                            host_id="bench-host-A", epoch_skew=3600.0)
    outs = [
        os.path.join(cache, f"serve_xhost_out{i}.bam") for i in range(2)
    ]
    for o in outs:
        client.submit(spool, in_path, o, config=config)
    out: dict = {"serve_xhost_hosts": 2, "serve_xhost_lease_s": lease_s}

    victim = ConsensusService(
        spool, chunk_budget=0, poll_s=0.02, lease_s=lease_s,
        daemon_id="xhost-victim", store=store_a,
        trace_path=os.path.join(
            spool, "service.xhost-victim.trace.jsonl"
        ),
    )
    orig_run_slice = victim.worker.run_slice

    def dying_run_slice(spec, budget, should_yield, drain_event,
                        lease=None):
        # one fresh chunk commits, then the yield check kills host A
        # with the lease still journaled — the modelled SIGKILL
        def die():
            raise faults.InjectedKill("serve_xhost: host A killed")

        return orig_run_slice(spec, 1, die, drain_event, lease=lease)

    victim.worker.run_slice = dying_run_slice
    t_dead = [0.0]

    def run_victim():
        try:
            victim.run_until_idle()
        except BaseException:  # noqa: BLE001 — the injected death
            t_dead[0] = time.monotonic()

    vt = threading.Thread(target=run_victim, daemon=True)
    vt.start()
    vt.join(timeout=600)
    if vt.is_alive() or not t_dead[0]:
        return {**out,
                "serve_xhost_error": "victim did not die on schedule"}
    q = SpoolQueue(spool)
    q.refresh()
    running = [
        jid for jid, e in q.jobs.items() if e.get("state") == "running"
    ]
    if not running:
        return {**out,
                "serve_xhost_error": "victim died holding no lease"}
    jid0 = running[0]

    store_b = resolve_store(spool, "sharedfs",
                            host_id="bench-host-B", epoch_skew=-3600.0)
    survivor = ConsensusService(
        spool, chunk_budget=0, poll_s=0.02, lease_s=lease_s,
        daemon_id="xhost-b", store=store_b,
        trace_path=os.path.join(spool, "service.xhost-b.trace.jsonl"),
    )
    st = threading.Thread(target=survivor.run_until_idle, daemon=True)
    st.start()
    takeover = None
    deadline = time.monotonic() + 300
    while time.monotonic() < deadline:
        q.refresh()
        e = q.jobs.get(jid0, {})
        if e.get("state") == "done" or (
            e.get("state") == "running"
            and (e.get("lease") or {}).get("owner") != "xhost-victim"
        ):
            takeover = time.monotonic() - t_dead[0]
            break
        time.sleep(0.005)
    st.join(timeout=600)
    q.refresh()
    n_done = sum(1 for e in q.jobs.values() if e.get("state") == "done")
    for o in outs:
        try:
            os.remove(o)
        except OSError:
            pass
    if n_done != len(outs):
        return {**out, "serve_xhost_error":
                f"fleet finished {n_done}/{len(outs)} jobs"}
    out.update({
        "serve_xhost_takeover_latency_s": (
            round(takeover, 3) if takeover is not None else None
        ),
        "serve_xhost_recovered": survivor.counters["jobs_recovered"],
    })
    return out


def run_serve_defense_bench() -> dict:
    """The ``serve_fleet`` poison/watchdog sub-leg: the defensive
    layer's two headline numbers, measured on the same tiny fleet
    workload (both informational, non-gating — they characterise the
    DEFENSE, not throughput):

      serve_quarantine_after_crashes  unclean aborts a deterministic
                                      poison job (injected kill at its
                                      first shard write, every run)
                                      survives before the fleet
                                      quarantines it — must equal the
                                      max_crashes bound, proving zero
                                      re-runs beyond it
      serve_watchdog_detect_latency_s wall from a slice wedging (lease
                                      alive, durable progress stopped)
                                      to the watchdog's abort-requeue
                                      landing in the journal
    """
    import shutil
    import threading

    from duplexumiconsensusreads_tpu.runtime import faults
    from duplexumiconsensusreads_tpu.serve import ConsensusService, client
    from duplexumiconsensusreads_tpu.serve.queue import SpoolQueue

    cache = os.environ.get("DUT_BENCH_CACHE", ".bench_cache")
    n_reads = int(os.environ.get("DUT_BENCH_SERVE_READS", 120_000))
    in_path, _ = _e2e_input(n_reads)
    config = dict(
        grouping="adjacency", mode="duplex", error_model="cycle",
        capacity=int(os.environ.get("DUT_BENCH_CAPACITY", 2048)),
        chunk_reads=max(n_reads // 4, 10_000),
    )
    out: dict = {}

    # ---- poison quarantine: crash-loop daemons until the fleet gives
    # up on the job; the count of daemon deaths IS the metric
    spool = os.path.join(cache, "serve_defense_spool")
    shutil.rmtree(spool, ignore_errors=True)
    poison_out = os.path.join(cache, "serve_defense_poison.bam")
    jid = client.submit(spool, in_path, poison_out, config=config,
                        chaos="shard.write:1:kill")
    deaths = 0
    for i in range(8):
        svc = ConsensusService(spool, chunk_budget=0, poll_s=0.02,
                               daemon_id=f"defense-{i}")
        try:
            svc.run_until_idle()
            break
        except faults.InjectedKill:
            deaths += 1
    q = SpoolQueue(spool)
    q.refresh()
    if q.jobs.get(jid, {}).get("state") != "quarantined":
        out["serve_defense_error"] = (
            f"poison job not quarantined after {deaths} daemon deaths"
        )
    else:
        out["serve_quarantine_after_crashes"] = deaths

    # ---- watchdog detect latency: wedge a slice deterministically and
    # time the journal's running -> queued transition
    spool2 = os.path.join(cache, "serve_defense_wd_spool")
    shutil.rmtree(spool2, ignore_errors=True)
    wd_out = os.path.join(cache, "serve_defense_wd.bam")
    jid2 = client.submit(spool2, in_path, wd_out, config=config)
    svc = ConsensusService(
        spool2, chunk_budget=1, poll_s=0.02, lease_s=3600.0,
        watchdog_s=0.5, daemon_id="defense-wd",
    )
    wedged = [0.0]
    release = threading.Event()
    orig = svc.worker.run_slice

    def wedging_run_slice(spec, budget, should_yield, drain_event,
                          lease=None):
        def wedge(*_a):
            if not wedged[0]:
                wedged[0] = time.monotonic()
            release.wait(timeout=120)
            return False

        return orig(spec, 1, wedge, drain_event, lease=lease)

    svc.worker.run_slice = wedging_run_slice
    th = threading.Thread(target=lambda: _swallow(svc.run_until_idle),
                          daemon=True)
    th.start()
    detect = None
    deadline = time.monotonic() + 240
    q2 = SpoolQueue(spool2)
    while time.monotonic() < deadline:
        if wedged[0]:
            q2.refresh()
            if q2.jobs.get(jid2, {}).get("state") == "queued":
                detect = time.monotonic() - wedged[0]
                break
        time.sleep(0.005)
    # un-wedge: the fenced slice unwinds, and the NEXT claim (the
    # requeued job) runs clean to completion so the leg ends idle
    svc.worker.run_slice = orig
    release.set()
    th.join(timeout=240)
    if detect is None:
        out["serve_defense_error"] = out.get(
            "serve_defense_error", "watchdog never fired on the wedge"
        )
    else:
        out["serve_watchdog_detect_latency_s"] = round(detect, 3)
    for p in (poison_out, wd_out):
        try:
            os.remove(p)
        except OSError:
            pass
    return out


def run_serve_shard_bench(n_daemons: int) -> dict:
    """The ``serve_shard`` leg: ONE large job through the fleet,
    unsharded (K=1 — through the full split/merge pipeline, proving
    the degenerate path costs only the merge copy) vs scattered at K=4
    across ``n_daemons`` in-process daemons sharing the spool.

    Emits (informational, non-gating — on a single host the daemons
    share the device, so the speedup mostly measures scheduling +
    pipeline-overlap headroom, not K-way device parallelism):

      serve_shard_speedup   wall(K=1) / wall(K=4), same input/config
      serve_shard_merge_s   the K=4 merge stage's wall (splice+index)
    """
    import shutil
    import threading

    from duplexumiconsensusreads_tpu.serve import ConsensusService, client
    from duplexumiconsensusreads_tpu.serve.queue import SpoolQueue

    cache = os.environ.get("DUT_BENCH_CACHE", ".bench_cache")
    n_reads = int(os.environ.get("DUT_BENCH_SERVE_READS", 120_000))
    in_path, _ = _e2e_input(n_reads)
    config = dict(
        grouping="adjacency", mode="duplex", error_model="cycle",
        capacity=int(os.environ.get("DUT_BENCH_CAPACITY", 2048)),
        chunk_reads=max(n_reads // 8, 10_000),
    )
    out: dict = {"serve_shard_daemons": n_daemons}
    # warm the process's jit cache first: the K=1 leg runs before the
    # K=4 leg, and without this it would pay the per-process XLA
    # compile the K=4 leg then gets for free — inflating the "speedup"
    # with compile amortisation the serve_n_jobs leg already measures
    warm_spool = os.path.join(cache, "serve_shard_warmup_spool")
    shutil.rmtree(warm_spool, ignore_errors=True)
    warm_out = os.path.join(cache, "serve_shard_warmup.bam")
    client.submit(warm_spool, in_path, warm_out, config=config)
    _swallow(ConsensusService(warm_spool, poll_s=0.02).run_until_idle)
    try:
        os.remove(warm_out)
    except OSError:
        pass
    walls: dict[int, float] = {}
    merge_s = None
    for k in (1, 4):
        spool = os.path.join(cache, f"serve_shard_spool_k{k}")
        shutil.rmtree(spool, ignore_errors=True)
        out_bam = os.path.join(cache, f"serve_shard_out_k{k}.bam")
        jid = client.submit(spool, in_path, out_bam, config=config, shards=k)
        svcs = [
            ConsensusService(spool, chunk_budget=0, poll_s=0.02,
                             daemon_id=f"shard-bench-{k}-{i}")
            for i in range(n_daemons)
        ]
        t0 = time.monotonic()
        threads = [
            threading.Thread(target=lambda s=s: _swallow(s.run_until_idle),
                             daemon=True)
            for s in svcs
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=1800)
        walls[k] = time.monotonic() - t0
        q = SpoolQueue(spool)
        q.refresh()
        entry = q.jobs.get(jid, {})
        if entry.get("state") != "done":
            return {**out, "serve_shard_error":
                    f"K={k} parent finished {entry.get('state')!r}"}
        st = q.status(jid)
        sharded = (st.get("result") or {}).get("sharded") or {}
        if k == 4:
            merge_s = sharded.get("merge_s")
        try:
            os.remove(out_bam)
        except OSError:
            pass
    out.update({
        "serve_shard_k1_wall_s": round(walls[1], 2),
        "serve_shard_k4_wall_s": round(walls[4], 2),
        "serve_shard_speedup": round(walls[1] / max(walls[4], 1e-9), 2),
    })
    if merge_s is not None:
        out["serve_shard_merge_s"] = merge_s
    return out


def _swallow(fn):
    try:
        fn()
    except BaseException:  # noqa: BLE001 — bench harness, never fatal
        pass


def run_bucket_tuner_bench() -> dict:
    """The ``bucket_tuner`` leg: MEASURED fill factors of the bucket
    auto-tuner on the canonical long-tail fixture, host-only (the CPU
    bench sim — no device leg needed: fill factor is a pure function of
    the group-size mix and the packer, and the byte-identity matrix in
    tests pins that the ladder never changes results).

    The fixture long-tails a simulated batch by merging its uniform
    position groups on a quadratic schedule (group j of the remap
    absorbs ~sqrt-growing runs), the shape hybrid panels actually have:
    many shallow tiles plus a hot tail — exactly where one global
    capacity pays tail padding on every flush. Emits:

      e2e_fill_factor              measured fill (real rows / padded
                                   row-slots) of build_buckets under
                                   the auto verdict's ladder
      bucket_tuner_fill_factor_off the single-capacity baseline fill
      tuner_predicted_speedup      the verdict's cost-model ratio
      tuner_ladder                 the chosen rungs
    """
    from duplexumiconsensusreads_tpu.bucketing import build_buckets
    from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
    from duplexumiconsensusreads_tpu.tuning import choose_ladder, group_sizes
    from duplexumiconsensusreads_tpu.types import GroupingParams

    capacity = int(os.environ.get("DUT_BENCH_CAPACITY", 2048))
    n_mol = int(os.environ.get("DUT_BENCH_TUNER_MOLECULES", 20_000))
    cfg = SimConfig(
        n_molecules=n_mol, read_len=150, n_positions=600,
        mean_family_size=3, umi_error=0.01, duplex=True, seed=17,
    )
    batch, _ = simulate_batch(cfg)
    # long-tail remap: consecutive uniform groups merge in runs cycling
    # 1..8, so merged group sizes span ~1x..8x the base tile depth —
    # the shallow-tiles-plus-hot-tail mix hybrid panels actually have,
    # all still below the capacity (oversized groups take the escapes
    # identically under every ladder and would dilute the measurement).
    # Exact grouping for the leg: merging positions can collide UMIs
    # across molecules, which only matters to adjacency semantics, and
    # this leg measures PACKING, not consensus (the matrix tests own
    # byte identity).
    pos = np.asarray(batch.pos_key)
    uniq, inv = np.unique(pos, return_inverse=True)
    merged = np.zeros(len(uniq), np.int64)
    m = j = 0
    while j < len(uniq):
        run = 1 + (m % 8)
        merged[j : j + run] = m
        j += run
        m += 1
    batch.pos_key[:] = merged[inv]
    gp = GroupingParams(strategy="exact")

    verdict = choose_ladder(group_sizes(batch), capacity, pack_mult=1)

    def measured_fill(ladder):
        bks = build_buckets(batch, capacity=capacity, grouping=gp,
                            ladder=ladder)
        real = sum(int(b.valid.sum()) for b in bks)
        pad = sum(b.capacity for b in bks)
        return round(real / max(pad, 1), 4)

    fill_off = measured_fill(None)
    fill_auto = (
        measured_fill(verdict.ladder) if len(verdict.ladder) > 1 else fill_off
    )
    return {
        "e2e_fill_factor": fill_auto,
        "bucket_tuner_fill_factor_off": fill_off,
        "tuner_predicted_speedup": verdict.predicted_speedup,
        "tuner_ladder": list(verdict.ladder),
        "bucket_tuner_reads": int(np.asarray(batch.valid).sum()),
    }


def run_cpu_e2e(n_target: int) -> dict:
    """The SAME streamed end-to-end pipeline forced onto the XLA-CPU
    backend (VERDICT r2 item 2: the >=50x north-star claim is about
    WALL-CLOCK, so it needs an end-to-end CPU denominator, not just a
    compute-vs-compute one). Runs in a subprocess (JAX_PLATFORMS is
    read at backend init) on a smaller cached input of the identical
    workload shape, scaled per-read; the consensus math is the same
    jitted pipeline, so the error rate matches by construction
    (bit-parity across backends is property-tested).
    """
    import subprocess
    import sys as _sys

    cache = os.environ.get("DUT_BENCH_CACHE", ".bench_cache")
    in_path, _ = _e2e_input(n_target)
    capacity = int(os.environ.get("DUT_BENCH_CAPACITY", 2048))
    out_path = os.path.join(cache, "e2e_cpu_out.bam")
    # the child re-imports _e2e_params, so both runs stream the same
    # input with the same params by construction
    child = f"""
import json, time
from duplexumiconsensusreads_tpu.utils.compile_cache import enable_compile_cache
enable_compile_cache({os.path.join(cache, "xla_cache_cpu")!r}, per_host_cpu=True)
from duplexumiconsensusreads_tpu.benchmark import (
    E2E_CHUNK_READS, E2E_MAX_INFLIGHT, _e2e_params,
)
from duplexumiconsensusreads_tpu.runtime.stream import stream_call_consensus
gp, cp = _e2e_params()
t0 = time.monotonic()
rep = stream_call_consensus(
    {in_path!r}, {out_path!r}, gp, cp,
    capacity={capacity},
    chunk_reads=E2E_CHUNK_READS, max_inflight=E2E_MAX_INFLIGHT,
)
wall = time.monotonic() - t0
print(json.dumps({{"reads": rep.n_records, "wall": wall,
                   "consensus": rep.n_consensus,
                   "phases": rep.seconds}}))
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # The denominator is as weather-sensitive as the numerator on this
    # contended 1-core box (r4: vs_vectorized_cpu swung 35.9 -> 48.6
    # between same-day runs). Run the subprocess >= 2x back to back —
    # strictly while the TPU is idle — and report the BEST run: the
    # fastest CPU is the honest denominator for a >= 50x claim
    # (VERDICT r4 item 4).
    reps = max(1, int(os.environ.get("DUT_BENCH_CPU_E2E_REPS", 2)))
    best = None
    walls = []
    try:
        for _ in range(reps):
            proc = subprocess.run(
                [_sys.executable, "-c", child], capture_output=True,
                text=True, env=env,
            )
            if proc.returncode != 0:
                sys.stderr.write(proc.stderr[-2000:])
                return {"cpu_e2e_error": f"exit {proc.returncode}"}
            r = json.loads(proc.stdout.strip().splitlines()[-1])
            walls.append(round(r["wall"], 2))
            if best is None or r["wall"] < best["wall"]:
                best = r
    finally:
        try:
            os.remove(out_path)
        except OSError:
            pass
    return {
        "cpu_e2e_reads": best["reads"],
        "cpu_e2e_wall_s": round(best["wall"], 2),
        "cpu_e2e_walls": walls,
        "cpu_e2e_reads_per_sec": round(best["reads"] / best["wall"], 1),
        "cpu_e2e_phases": {
            k: v for k, v in best["phases"].items() if k != "total"
        },
    }


def main() -> None:
    import jax

    from duplexumiconsensusreads_tpu.utils.compile_cache import enable_compile_cache

    # benchmark compiles persist beside the benchmark input cache, so
    # every round after the first skips the 20-40s-per-geometry compiles
    enable_compile_cache(
        os.path.join(os.environ.get("DUT_BENCH_CACHE", ".bench_cache"), "xla_cache")
    )

    from duplexumiconsensusreads_tpu.bucketing import build_buckets, stack_buckets
    from duplexumiconsensusreads_tpu.ops import ConsensusCaller
    from duplexumiconsensusreads_tpu.oracle import group_reads
    from duplexumiconsensusreads_tpu.parallel import make_mesh
    from duplexumiconsensusreads_tpu.parallel.sharded import (
        presharded_pipeline,
        shard_stacked,
    )
    from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
    from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

    # ~600k reads/dispatch amortises the tunnel's fixed ~100ms per-call
    # latency while staying inside HBM (1M+ reads/dispatch OOMs: the
    # contributions + one-hot intermediates scale with bucket count)
    n_target = int(os.environ.get("DUT_BENCH_READS", 600_000))
    capacity = int(os.environ.get("DUT_BENCH_CAPACITY", 2048))
    cpu_sample = int(os.environ.get("DUT_BENCH_CPU_SAMPLE", 3000))

    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex", error_model="cycle", min_duplex_reads=1)

    # ~9 reads per molecule (both strands); ~150 bp reads, panel-like tiling
    n_mol = max(64, n_target // 9)
    t0 = time.monotonic()
    sim_cfg = SimConfig(
        n_molecules=n_mol,
        read_len=150,
        n_positions=max(8, n_mol // 48),
        mean_family_size=4,
        umi_error=0.01,
        duplex=True,
        seed=7,
    )
    batch, truth = simulate_batch(sim_cfg)
    n_reads = int(np.asarray(batch.valid).sum())
    buckets = build_buckets(batch, capacity=capacity, grouping=gp)
    sim_s = time.monotonic() - t0

    n_dev = len(jax.devices())
    mesh = make_mesh(n_dev)

    # dispatch classes (capacity/preclustered/unique-count) exactly as
    # the production executor would — oversized position groups and
    # jumbo families get their own geometry + strategy
    from duplexumiconsensusreads_tpu.runtime.executor import (
        default_ssc_method,
        partition_buckets,
    )

    ssc_method = os.environ.get("DUT_SSC_METHOD", default_ssc_method())
    if ssc_method not in ("matmul", "blockseg", "runsum", "segment", "pallas"):
        raise SystemExit(f"DUT_SSC_METHOD: unknown method {ssc_method!r}")
    part = partition_buckets(buckets, gp, cp, ssc_method)
    # device-put once (sharded); timed loop measures pure compute, not
    # host->device transfer of the input tensors
    classes = []
    for cbuckets, cspec in part:
        stacked = stack_buckets(cbuckets, multiple_of=n_dev)
        classes.append((cbuckets, cspec, shard_stacked(stacked, mesh)))
    jax.block_until_ready([c[2] for c in classes])

    def run_all():
        return [presharded_pipeline(args, cspec, mesh) for _, cspec, args in classes]

    # compile (excluded from timing). NOTE: timing ends with a small
    # device->host read — on remote-tunneled platforms block_until_ready
    # alone returns before execution finishes, silently inflating
    # throughput by 100-1000x.
    t0 = time.monotonic()
    for o in run_all():
        np.asarray(o["n_families"])
    compile_s = time.monotonic() - t0

    # Steps are dispatched asynchronously and synced once at the end:
    # that is exactly how the streaming executor overlaps chunks, and it
    # amortises fixed per-call dispatch latency (~100ms on a tunneled
    # chip) that would otherwise dominate the per-step number. ONE
    # fetch of the final program's output suffices as the barrier —
    # a TPU executes programs in order, so the last completing implies
    # all completed (per-class fetches each paid a tunnel RTT; measured
    # +7% on the r3 box).
    reps = int(os.environ.get("DUT_BENCH_REPS", 10))
    t0 = time.monotonic()
    outs = [run_all() for _ in range(reps)]
    np.asarray(outs[-1][-1]["n_families"])
    tpu_s = (time.monotonic() - t0) / reps
    tpu_rps = n_reads / tpu_s

    # analytic executed-FLOP accounting -> TFLOP/s and MFU (VERDICT r1
    # item 4): per-class geometry x padded bucket count, over the
    # measured step time. Peak from the shared device table
    # (telemetry/device.py) keyed on the local device kind —
    # DUT_PEAK_TFLOPS env override wins, cpu-sim deliberately keeps the
    # v5e 197 so the CPU-leg trajectory stays comparable across rounds.
    from duplexumiconsensusreads_tpu.ops.pipeline import analytic_flops
    from duplexumiconsensusreads_tpu.telemetry.device import device_peak_flops

    l_ = batch.read_len
    b_ = batch.umi_len
    step_flops = sum(
        analytic_flops(cspec, cbuckets[0].capacity, l_, b_)
        * args["pos"].shape[0]
        for cbuckets, cspec, args in classes
    )
    peak, peak_entry = device_peak_flops()
    tflops = step_flops / tpu_s / 1e12
    mfu = step_flops / tpu_s / peak

    # consensus error rate vs simulation truth (the "matched error
    # rate" side of the metric): map each consensus molecule to its
    # true molecule through a member read, compare called bases
    class_outs = [
        ({k: np.asarray(v) for k, v in o.items()}, cbuckets)
        for o, (cbuckets, _, _) in zip(outs[-1], classes)
    ]
    n_err = n_base = 0
    for out_np, cbuckets in class_outs:
        for bi, bk in enumerate(cbuckets):
            mol = out_np["molecule_id"][bi]
            cv = out_np["cons_valid"][bi]
            ridx = bk.read_index
            sel = np.nonzero((ridx >= 0) & bk.valid & (mol >= 0))[0]
            if not len(sel):
                continue
            ms = mol[sel]
            order = np.argsort(ms, kind="stable")
            first = np.nonzero(np.r_[True, ms[order][1:] != ms[order][:-1]])[0]
            rep_mol = ms[order][first]  # molecule rows present in bucket
            rep_read = ridx[sel[order[first]]]  # one member read each
            true_rows = truth.mol_seq[truth.read_mol[rep_read]]
            called = out_np["cons_base"][bi][rep_mol]
            real = (called < 4) & cv[rep_mol][:, None]
            n_err += int((called[real] != true_rows[real]).sum())
            n_base += int(real.sum())
    err_rate = n_err / max(n_base, 1)

    # CPU-oracle baseline on a subsample, scaled per-read
    sub_idx = np.nonzero(np.asarray(batch.valid))[0][:cpu_sample]
    sub = batch.take(sub_idx)
    t0 = time.monotonic()
    fams = group_reads(sub, gp)
    ConsensusCaller(cp, backend="cpu")(sub, fams)
    cpu_s = time.monotonic() - t0
    cpu_rps = len(sub_idx) / cpu_s

    # Vectorized CPU baseline (VERDICT r1 item 8): the SAME fused
    # pipeline XLA-compiled for host CPU — a competent vectorized CPU
    # implementation, not a per-family Python loop. The >=50x claim is
    # judged against this number too.
    from duplexumiconsensusreads_tpu.ops import run_bucket

    import dataclasses as _dc

    from duplexumiconsensusreads_tpu.runtime.executor import DEFAULT_SSC_METHOD_CPU

    cpu_dev = jax.devices("cpu")[0]
    target = int(os.environ.get("DUT_BENCH_VEC_SAMPLE", 30_000))
    sample, got = [], 0
    for cbuckets, cspec, _ in classes:
        # the CPU baseline runs its own best-measured reduction (r3:
        # blockseg, 4.2x faster than matmul on a scalar core) — a
        # baseline hobbled with the TPU-optimal method would flatter us
        cpu_spec = _dc.replace(cspec, ssc_method=DEFAULT_SSC_METHOD_CPU)
        for bk in cbuckets:
            sample.append((bk, cpu_spec))
            got += int(bk.valid.sum())
            if got >= target:
                break
        if got >= target:
            break
    # the in-process XLA:CPU compiles must NOT share the TPU cache dir:
    # CPU AOT artifacts encode the compile host's feature flags, and a
    # host change between rounds makes stale ones SIGILL mid-execution
    # (observed r5 — the bench segfaulted right after this phase).
    # Redirect to the host-fingerprinted CPU cache, restore after.
    from duplexumiconsensusreads_tpu.utils.compile_cache import (
        enable_compile_cache as _ecc,
    )

    tpu_cache = os.path.join(
        os.environ.get("DUT_BENCH_CACHE", ".bench_cache"), "xla_cache"
    )
    _ecc(
        os.path.join(
            os.environ.get("DUT_BENCH_CACHE", ".bench_cache"),
            "xla_cache_cpu",
        ),
        per_host_cpu=True,
    )
    try:
        with jax.default_device(cpu_dev):
            outs = [run_bucket(bk, cs) for bk, cs in sample]  # compile
            jax.block_until_ready(outs)
            # best of N timed passes: the 1-core box's scheduling noise
            # hits the denominator too, and the fastest CPU pass is the
            # honest one for the >= 50x claim (VERDICT r4 item 4)
            vec_reps = max(1, int(os.environ.get("DUT_BENCH_VEC_REPS", 3)))
            vec_cpu_s = float("inf")
            for _ in range(vec_reps):
                t0 = time.monotonic()
                outs = [run_bucket(bk, cs) for bk, cs in sample]
                jax.block_until_ready(outs)
                vec_cpu_s = min(vec_cpu_s, time.monotonic() - t0)
    finally:
        _ecc(tpu_cache)
    vec_cpu_rps = got / max(vec_cpu_s, 1e-9)

    result = {
        "metric": "reads_per_sec_duplex_consensus",
        "value": round(tpu_rps, 1),
        "unit": "reads/s",
        "vs_baseline": round(tpu_rps / cpu_rps, 2),
        "tflops": round(tflops, 2),
        "mfu": round(mfu, 4),
        # which peak-table row (or env override) scored the MFU — an
        # MFU without its denominator's provenance is unauditable
        "peak_entry": peak_entry,
        "vs_vectorized_cpu": round(tpu_rps / vec_cpu_rps, 2),
        "ssc_method": ssc_method,
    }

    # ---- per-config compute matrix (VERDICT r3 item 4) ----
    if int(os.environ.get("DUT_BENCH_PER_CONFIG", 1)):
        result["per_config"] = run_per_config(mesh)

    # ---- bucket_tuner leg: measured fill factors of the auto-tuner on
    # the canonical long-tail fixture (host-only, cheap; DUT_BENCH_TUNER=0
    # disables) ----
    if int(os.environ.get("DUT_BENCH_TUNER", 1)):
        result.update(run_bucket_tuner_bench())

    # ---- end-to-end phase: wall-clock through the streaming pipeline.
    # Phase order is pinned (VERDICT r4 item 4): wire probe, TPU e2e,
    # wire probe again, the packed/unpacked A/B pair, then the CPU
    # denominator runs strictly after all device work is idle.
    n_e2e = int(os.environ.get("DUT_BENCH_E2E_READS", 10_000_000))
    if n_e2e > 0:
        probe0 = wire_probe()
        result["wire_before_e2e"] = probe0
        e2e = run_e2e(n_e2e)
        result.update(e2e)
        result["e2e_vs_compute"] = round(
            e2e["e2e_reads_per_sec"] / tpu_rps, 3
        )
        probe1 = wire_probe()
        result["wire_after_e2e"] = probe1
        # arithmetic wall floor: measured bytes over measured wire,
        # bracketed by the probes on either side of the run. When
        # frac ~ 1 the JSON itself proves the tunnel, not the code, set
        # the wall (VERDICT r4 item 1: "tunnel weather" must be a
        # measured per-capture fact, not an assertion)
        floors = [
            e2e["e2e_h2d_mb"] / p["wire_h2d_mb_s"]
            + e2e["e2e_d2h_mb"] / p["wire_d2h_mb_s"]
            for p in (probe0, probe1)
        ]
        result["e2e_wire_floor_s"] = [round(min(floors), 1), round(max(floors), 1)]
        result["e2e_wire_floor_frac"] = [
            round(min(floors) / e2e["e2e_wall_s"], 2),
            round(max(floors) / e2e["e2e_wall_s"], 2),
        ]
        # same-run packed-vs-unpacked A/B: BOTH legs run here, same
        # size, adjacent in time, warm caches — r4's guard compared a
        # full-size unpacked leg against a budget the packed leg had
        # already blown, so it self-disabled on exactly the host it was
        # built for and erased the round's A/B evidence (VERDICT r4
        # weak 1). Now the legs SHRINK to fit the budget instead of
        # skipping; DUT_BENCH_E2E_AB=0 disables.
        n_ab = int(os.environ.get("DUT_BENCH_E2E_AB", 2_000_000))
        ab_budget = float(os.environ.get("DUT_BENCH_AB_BUDGET_S", 480))
        if n_ab > 0:
            exp_s = 2.0 * n_ab / max(e2e["e2e_reads_per_sec"], 1.0)
            if ab_budget > 0 and exp_s > ab_budget:
                # quantize to whole chunks: the leg size feeds the
                # input-BAM cache key, and a weather-dependent arbitrary
                # integer would simulate+cache a fresh multi-hundred-MB
                # input on every budget-limited run (review r5 finding)
                n_ab = min(
                    n_ab,
                    max(
                        int(n_ab * ab_budget / exp_s) // E2E_CHUNK_READS,
                        1,
                    ) * E2E_CHUNK_READS,
                )
                result["e2e_ab_shrunk_to"] = n_ab
            packed_leg = run_e2e(n_ab, packed="auto", prefix="e2e_ab_packed")
            result.update(packed_leg)
            unpacked = run_e2e(
                n_ab, packed="off", d2h_packed="off", prefix="e2e_unpacked"
            )
            result.update(unpacked)
            # same fully-unpacked baseline as r1-r5, so the trajectory
            # stays readable: the speedup now also carries the sub-byte
            # H2D rung and the packed return path
            result["e2e_packed_speedup"] = round(
                packed_leg["e2e_ab_packed_reads_per_sec"]
                / unpacked["e2e_unpacked_reads_per_sec"],
                3,
            )
            # d2h A/B: same H2D rung, packed vs unpacked return path —
            # isolates what the consensus-only compaction buys
            d2h_off = run_e2e(
                n_ab, packed="auto", d2h_packed="off",
                prefix="e2e_d2h_unpacked",
            )
            result.update(d2h_off)
            result["e2e_d2h_packed_speedup"] = round(
                packed_leg["e2e_ab_packed_reads_per_sec"]
                / d2h_off["e2e_d2h_unpacked_reads_per_sec"],
                3,
            )
            # ingest-overlap A/B: the same leg with the background
            # producer disabled — what pipelining BGZF/decode/bucketing
            # under device compute buys end-to-end. The packed leg
            # above already ran with overlap on (auto), so only the
            # off leg costs extra wall. Ratio is off-wall/on-wall, so
            # >= 1.111 means overlap-on runs at <= 0.9x the sync wall.
            # DUT_BENCH_INGEST_AB=0 disables.
            if int(os.environ.get("DUT_BENCH_INGEST_AB", 1)):
                ov_off = run_e2e(
                    n_ab, ingest_overlap="off", prefix="e2e_ov_off"
                )
                result.update(ov_off)
                result["e2e_ingest_overlap"] = round(
                    ov_off["e2e_ov_off_wall_s"]
                    / max(packed_leg["e2e_ab_packed_wall_s"], 1e-9),
                    3,
                )
            # mesh-scaling A/B (DUT_BENCH_MESH=K, needs K devices —
            # simulated on CPU via XLA_FLAGS, real chips on a pod):
            # the same leg at K devices vs 1, same warm caches. On the
            # simulated-device CPU path the ratio is informational
            # (virtual devices share the host's cores); on real
            # silicon it IS the K-way scaling headline.
            n_mesh = int(os.environ.get("DUT_BENCH_MESH", 0))
            if n_mesh > 1:
                import jax as _jax

                if len(_jax.devices()) >= n_mesh:
                    mesh_leg = run_e2e(
                        n_ab, prefix="e2e_meshk", n_devices=n_mesh
                    )
                    result.update(mesh_leg)
                    mesh_one = run_e2e(
                        n_ab, prefix="e2e_mesh1", n_devices=1
                    )
                    result.update(mesh_one)
                    result["e2e_mesh_devices"] = n_mesh
                    result["e2e_mesh_scaling"] = round(
                        mesh_one["e2e_mesh1_wall_s"]
                        / max(mesh_leg["e2e_meshk_wall_s"], 1e-9),
                        3,
                    )
                else:
                    result["e2e_mesh_error"] = (
                        f"DUT_BENCH_MESH={n_mesh} but only "
                        f"{len(_jax.devices())} devices visible"
                    )
        # serve_n_jobs: small jobs through the in-process daemon vs a
        # cold one-shot subprocess — the serving layer's compile
        # amortisation, measured (DUT_BENCH_SERVE_JOBS=0 disables).
        # Runs before the CPU denominator: it uses the device.
        n_serve = int(os.environ.get("DUT_BENCH_SERVE_JOBS", 3))
        if n_serve > 0:
            result.update(run_serve_bench(n_serve))
        # serve_fleet: jobs through N in-process daemons on ONE spool,
        # with daemon 0 killed mid-job — measures dead-daemon takeover
        # latency and per-class queue-wait under the lease protocol
        # (DUT_BENCH_SERVE_DAEMONS<2 disables)
        n_fleet = int(os.environ.get("DUT_BENCH_SERVE_DAEMONS", 2))
        if n_serve > 0 and n_fleet >= 2:
            result.update(run_serve_fleet_bench(n_fleet))
            # defensive-serving sub-leg: poison-job quarantine depth +
            # watchdog detect latency (informational, non-gating)
            result.update(run_serve_defense_bench())
            # scatter-gather sub-leg: one large job at K=1 vs K=4
            # across the same fleet (informational, non-gating)
            result.update(run_serve_shard_bench(n_fleet))
            # cross-host sub-leg: the takeover scenario on the
            # sharedfs lease store — two synthetic hosts with skewed
            # epochs; detection is translated lease expiry, never a
            # pid probe (informational, non-gating)
            result.update(run_serve_xhost_bench())
        # live_follow: the follower tailing a BAM a paced writer is
        # still appending — first-snapshot latency + steady lag
        # (informational, non-gating; DUT_BENCH_LIVE_READS=0 disables)
        if int(os.environ.get("DUT_BENCH_LIVE_READS", 120_000)) > 0:
            result.update(run_live_follow_bench())
        # same pipeline end-to-end on XLA-CPU: the wall-clock >=50x
        # denominator (DUT_BENCH_CPU_E2E_READS=0 disables); runs after
        # every TPU leg so the 1-core box is never shared
        n_cpu_e2e = int(os.environ.get("DUT_BENCH_CPU_E2E_READS", 1_000_000))
        if n_cpu_e2e > 0:
            cpu_e2e = run_cpu_e2e(n_cpu_e2e)
            result.update(cpu_e2e)
            if "cpu_e2e_reads_per_sec" in cpu_e2e:
                result["e2e_vs_cpu_e2e"] = round(
                    e2e["e2e_reads_per_sec"] / cpu_e2e["cpu_e2e_reads_per_sec"],
                    2,
                )
    # human journal FIRST (stderr, flushed), then the parseable JSON
    # LAST on stdout — and since r5 proved the driver's tail window is
    # ~2000 bytes, "parseable" now means the COMPACT canonical line
    # (see COMPACT_KEYS): the full result rides the line above it and
    # is mirrored to <cache>/bench_full.json for post-mortem
    print(
        f"# reads={n_reads} buckets={len(buckets)} devices={n_dev} "
        f"bucket_capacity={capacity} tpu_step={tpu_s:.3f}s compile={compile_s:.1f}s "
        f"cpu_oracle={cpu_rps:.0f} reads/s (n={len(sub_idx)}) "
        f"vec_cpu={vec_cpu_rps:.0f} reads/s (n={got}, XLA-CPU fused pipeline) "
        f"tflops={tflops:.2f} mfu={mfu:.4f} "
        f"(peak={peak/1e12:.0f}T [{peak_entry}]) sim={sim_s:.1f}s "
        f"consensus_error_rate={err_rate:.2e} ({n_err}/{n_base} bases, "
        f"raw base_error={sim_cfg.base_error:g}) "
        f"ssc_method={ssc_method} (r2 in-pipeline on v5e: matmul fastest "
        f"vs segment 1.26x / pallas 1.59x slower; r3 adds blockseg/runsum "
        f"— see DUT_SSC_METHOD and the BENCH_r03 journal)",
        file=sys.stderr,
        flush=True,
    )
    full_path = os.path.join(
        os.environ.get("DUT_BENCH_CACHE", ".bench_cache"), "bench_full.json"
    )
    try:
        with open(full_path, "w") as f:
            json.dump(result, f)
    except OSError:
        full_path = None
    gate_failed = False
    compact = compact_result(result, full_path)
    if int(os.environ.get("DUT_BENCH_GATE", 1)):
        gate_ok, gate_problems = run_bench_gate(result)
        if gate_problems:
            # bounded: the compact line must stay inside the window
            compact["gate_regressions"] = [p[:160] for p in gate_problems[:3]]
        if not gate_ok:
            print(
                "# BENCH GATE FAILED: canonical metrics regressed vs the "
                "recorded trajectory — " + "; ".join(gate_problems),
                file=sys.stderr,
                flush=True,
            )
            gate_failed = True
    print(json.dumps(result), flush=True)
    print(json.dumps(compact), flush=True)
    if gate_failed:
        # the regression fails the run VISIBLY (the bench is a gate,
        # not a diary) — after the result lines, so the driver still
        # records the round it is failing
        raise SystemExit(1)


if __name__ == "__main__":
    main()
