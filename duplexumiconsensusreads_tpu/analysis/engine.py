"""Lint engine: corpus loading, rule registry, allowlist, findings.

Deliberately stdlib-``ast`` only (the container has no flake8 plugins,
and these rules are project-semantic anyway). The engine is dumb on
purpose: it parses a set of files once, hands every rule the whole
parsed corpus (rules are routinely CROSS-file — a fault site is a
property of faults.py, its call sites, and the chaos suite at once),
and matches the resulting findings against the allowlist.

Allowlist contract: an entry is (rule, path, reason) — suppression is
per rule per file, never blanket, and every entry must carry a reason
so the exception stays audited. Entries that suppress nothing are
reported back (``unused``) so the list cannot silently rot after the
underlying code is fixed.
"""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Callable, Iterable, Iterator


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation, anchored to a file:line, with a fix hint."""

    rule: str
    path: str  # corpus-relative posix path
    line: int
    message: str
    hint: str = ""

    def format(self) -> str:
        s = f"{self.path}:{self.line}: [{self.rule}] {self.message}"
        if self.hint:
            s += f"  (fix: {self.hint})"
        return s


@dataclasses.dataclass(frozen=True)
class AllowEntry:
    """One intentional exception: suppresses every finding of ``rule``
    in ``path``. ``reason`` is mandatory — an unexplained suppression
    is indistinguishable from a forgotten one."""

    rule: str
    path: str
    reason: str

    def __post_init__(self):
        if not self.reason.strip():
            raise ValueError(
                f"allowlist entry ({self.rule}, {self.path}) needs a reason"
            )


class Corpus:
    """A parsed file set: corpus-relative posix path -> (source, AST).

    Every AST node carries a ``_lint_parent`` backpointer so rules can
    walk ancestor chains (lock bodies, guard ``if``s, enclosing
    functions) without reimplementing scope tracking each time.
    """

    def __init__(self, root: str):
        self.root = root
        self.sources: dict[str, str] = {}
        self.trees: dict[str, ast.Module] = {}
        self.parse_failures: list[Finding] = []

    def add(self, rel_path: str, source: str) -> None:
        rel_path = rel_path.replace(os.sep, "/")
        self.sources[rel_path] = source
        try:
            tree = ast.parse(source, filename=rel_path)
        except SyntaxError as e:
            # a file the linter cannot parse is itself a finding — the
            # invariants it might violate are unverifiable
            self.parse_failures.append(
                Finding(
                    rule="parse",
                    path=rel_path,
                    line=e.lineno or 1,
                    message=f"file does not parse: {e.msg}",
                    hint="fix the syntax error so the linter can see the file",
                )
            )
            return
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                child._lint_parent = node  # type: ignore[attr-defined]
        self.trees[rel_path] = tree

    def find(self, suffix: str) -> str | None:
        """The corpus path ending with ``suffix`` (posix), or None.
        Anchor files (faults.py, trace.py, the chaos suite) are located
        this way so fixture corpora in tests can mirror the layout
        under any root."""
        suffix = suffix.replace(os.sep, "/")
        for p in self.trees:
            if p == suffix or p.endswith("/" + suffix):
                return p
        return None

    def package_paths(self) -> list[str]:
        """Paths inside the package proper (not tools/, not tests/)."""
        return [
            p for p in self.trees
            if not p.startswith(("tools/", "tests/")) and "/tests/" not in p
        ]


# Shared AST cache: (abspath) -> (mtime_ns, size, source, tree).
# The tier-1 suite and the CLI load the same ~90-file corpus dozens of
# times per run (whole-tree gate, per-rule bisections, ci_check legs);
# parsing is the dominant cost, and trees are never mutated by rules,
# so identical on-disk files share one parse. Keyed by mtime+size so
# an edited file re-parses; in-memory fixture corpora (Corpus.add) are
# not cached. CACHE_STATS backs the lint-suite runtime budget test.
_AST_CACHE: dict[str, tuple[int, int, str, ast.Module]] = {}
CACHE_STATS = {"hits": 0, "misses": 0}


def load_corpus(root: str, rel_paths: Iterable[str]) -> Corpus:
    corpus = Corpus(root)
    for rel in sorted(set(rel_paths)):
        full = os.path.join(root, rel)
        key = os.path.abspath(full)
        st = os.stat(full)
        cached = _AST_CACHE.get(key)
        if (
            cached is not None
            and cached[0] == st.st_mtime_ns
            and cached[1] == st.st_size
        ):
            CACHE_STATS["hits"] += 1
            rel_posix = rel.replace(os.sep, "/")
            corpus.sources[rel_posix] = cached[2]
            corpus.trees[rel_posix] = cached[3]
            continue
        CACHE_STATS["misses"] += 1
        with open(full, "r", encoding="utf-8") as f:
            source = f.read()
        corpus.add(rel, source)
        rel_posix = rel.replace(os.sep, "/")
        tree = corpus.trees.get(rel_posix)
        if tree is not None:  # parse failures are re-reported per load
            _AST_CACHE[key] = (st.st_mtime_ns, st.st_size, source, tree)
    return corpus


# --------------------------------------------------------- rule registry

@dataclasses.dataclass(frozen=True)
class Rule:
    id: str
    title: str
    check: Callable[[Corpus], Iterator[Finding]]


RULES: dict[str, Rule] = {}


def register(rule_id: str, title: str):
    """Decorator: add a check function to the registry under ``rule_id``."""

    def deco(fn):
        if rule_id in RULES:
            raise ValueError(f"duplicate rule id {rule_id!r}")
        RULES[rule_id] = Rule(rule_id, title, fn)
        return fn

    return deco


# --------------------------------------------------------------- running

@dataclasses.dataclass
class LintResult:
    findings: list[Finding]  # non-suppressed, sorted
    suppressed: list[tuple[Finding, AllowEntry]]
    unused_allowlist: list[AllowEntry]

    @property
    def ok(self) -> bool:
        return not self.findings


def run_lint(
    corpus: Corpus,
    allowlist: Iterable[AllowEntry] = (),
    only_rules: Iterable[str] | None = None,
) -> LintResult:
    """Run every registered rule (or ``only_rules``) over ``corpus``."""
    allow = list(allowlist)
    rule_ids = list(only_rules) if only_rules is not None else sorted(RULES)
    unknown = [r for r in rule_ids if r not in RULES]
    if unknown:
        raise ValueError(
            f"unknown rule id(s): {', '.join(unknown)} "
            f"(known: {', '.join(sorted(RULES))})"
        )
    raw: list[Finding] = list(corpus.parse_failures)
    for rid in rule_ids:
        raw.extend(RULES[rid].check(corpus))
    kept: list[Finding] = []
    suppressed: list[tuple[Finding, AllowEntry]] = []
    used: set[int] = set()
    for f in raw:
        entry = next(
            (a for a in allow if a.rule == f.rule and a.path == f.path), None
        )
        if entry is not None:
            suppressed.append((f, entry))
            used.add(id(entry))
        else:
            kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule, f.message))
    unused = [
        a for a in allow
        if id(a) not in used
        # an entry for a rule that wasn't run can't have fired; only
        # call it unused when its rule actually participated
        and (a.rule in rule_ids or a.rule == "parse")
    ]
    return LintResult(kept, suppressed, unused)


# ----------------------------------------------------------- AST helpers
#
# Shared by several rules; kept here so rules.py stays about the
# invariants, not AST plumbing.

def call_name(node: ast.Call) -> str:
    """Terminal callee name: ``open(...)`` -> "open",
    ``tr.span(...)`` -> "span", ``faults.fault_point(...)`` ->
    "fault_point". Empty string for exotic callees."""
    fn = node.func
    if isinstance(fn, ast.Name):
        return fn.id
    if isinstance(fn, ast.Attribute):
        return fn.attr
    return ""


def str_const(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def str_tuple_assign(tree: ast.Module, name: str) -> tuple[list[str], int]:
    """Module-level ``NAME = ("a", "b", ...)`` -> (values, lineno).

    Returns ([], 0) when the assignment is missing or not a literal
    string tuple/list — callers treat that as "registry not found"."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == name:
                val = node.value
                if isinstance(val, (ast.Tuple, ast.List)):
                    out = [str_const(e) for e in val.elts]
                    if all(v is not None for v in out):
                        return [v for v in out if v is not None], node.lineno
    return [], 0


def str_dict_assign(
    tree: ast.Module, name: str
) -> tuple[dict[str, tuple[str, ...]], int]:
    """Module-level ``NAME = {"a": ("b", ...), ...}`` -> (dict, lineno).

    The declared-graph shape (the state machine's TRANSITIONS table):
    string keys, tuple/list-of-string values. Returns ({}, 0) when the
    assignment is missing or not fully literal — callers treat that as
    "registry not found", same contract as :func:`str_tuple_assign`."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        for t in targets:
            if not (isinstance(t, ast.Name) and t.id == name):
                continue
            val = node.value
            if not isinstance(val, ast.Dict):
                continue
            out: dict[str, tuple[str, ...]] = {}
            ok = True
            for k, v in zip(val.keys, val.values):
                key = str_const(k) if k is not None else None
                if key is None or not isinstance(v, (ast.Tuple, ast.List)):
                    ok = False
                    break
                elts = [str_const(e) for e in v.elts]
                if any(e is None for e in elts):
                    ok = False
                    break
                out[key] = tuple(e for e in elts if e is not None)
            if ok and out:
                return out, node.lineno
    return {}, 0


def literal_assign(tree: ast.Module, name: str):
    """Module-level ``NAME = <pure literal>`` -> the evaluated Python
    value (via ``ast.literal_eval``), or None when the assignment is
    missing or not a literal. The registry-reading contract for the
    declared-model rules (KNOB_TABLE, THREAD_ROLES): registries are
    read FROM THE CORPUS, never imported, so fixture corpora declare
    their own miniatures and "not a literal" degrades to "registry not
    found" like :func:`str_tuple_assign`."""
    for node in tree.body:
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        for t in targets:
            if isinstance(t, ast.Name) and t.id == name:
                try:
                    return ast.literal_eval(node.value)
                except (ValueError, SyntaxError, TypeError):
                    return None
    return None


def function_defs(tree: ast.Module) -> dict[str, ast.AST]:
    """Every (async) function def in the file by name, nested defs
    included (thread entries and their closures live inside
    ``stream_call_consensus``). First definition wins, so the mapping
    is deterministic."""
    defs: dict[str, ast.AST] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            defs.setdefault(node.name, node)
    return defs


def reachable_functions(
    defs: dict[str, ast.AST], root_name: str
) -> list[ast.AST]:
    """``root_name`` plus every same-file function it (transitively)
    calls by name — a thread entry's static call scope. Imported
    callees are out of scope: they are the shared vocabulary of the
    whole program and carry their own rules."""
    if root_name not in defs:
        return []
    scope = {root_name}
    frontier = [defs[root_name]]
    while frontier:
        fn = frontier.pop()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in defs and name not in scope:
                scope.add(name)
                frontier.append(defs[name])
    return [defs[n] for n in sorted(scope)]


def inside_named_lock(node: ast.AST, lock_name: str) -> bool:
    """Is ``node`` lexically inside ``with <lock_name>:``? Name-based
    like :func:`inside_lock_body`, but for ONE declared lock — the
    thread-confinement registry names which lock guards which shared
    structure, so "some lock" is not good enough."""
    for a in ancestors(node):
        if not isinstance(a, (ast.With, ast.AsyncWith)):
            continue
        for item in a.items:
            for n in ast.walk(item.context_expr):
                if isinstance(n, ast.Name) and n.id == lock_name:
                    return True
                if isinstance(n, ast.Attribute) and n.attr == lock_name:
                    return True
    return False


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = getattr(node, "_lint_parent", None)
    while cur is not None:
        yield cur
        cur = getattr(cur, "_lint_parent", None)


def enclosing_function(node: ast.AST) -> ast.AST | None:
    for a in ancestors(node):
        if isinstance(a, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return a
    return None


def node_mentions_lock(node: ast.AST) -> bool:
    """Does this expression reference something named like a lock?
    (``phase_lock``, ``self._lock``, ``lock`` — name-based on purpose:
    the codebase's convention IS the name.)"""
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and "lock" in n.id.lower():
            return True
        if isinstance(n, ast.Attribute) and "lock" in n.attr.lower():
            return True
    return False


def inside_lock_body(node: ast.AST) -> bool:
    """Is ``node`` lexically inside a ``with <lock>:`` body?"""
    for a in ancestors(node):
        if isinstance(a, (ast.With, ast.AsyncWith)) and any(
            node_mentions_lock(item.context_expr) for item in a.items
        ):
            return True
    return False


def expr_path(node: ast.AST) -> str | None:
    """Dotted-name path of a Name/Attribute chain (``tr`` ->
    "tr", ``self._recorder`` -> "self._recorder"); None for anything
    else (calls, subscripts) — those have no stable identity to match
    a guard against."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = expr_path(node.value)
        return None if base is None else f"{base}.{node.attr}"
    return None


def guarded_not_none(node: ast.AST, var: str) -> bool:
    """Is ``node`` inside the branch of an ``if`` proving ``var`` (a
    dotted-name path) is not None? Accepts ``if var is not None:
    <body>``, ``if var is None: ... else: <body>``, and ``var is not
    None`` as a conjunct of an ``and`` (``if var is not None and
    resume:``)."""

    def _cmp(test: ast.AST) -> str | None:
        # returns "not_none" / "none" when test proves it for `var`
        if isinstance(test, ast.BoolOp) and isinstance(test.op, ast.And):
            # every conjunct of an `and` holds in the body
            if any(_cmp(v) == "not_none" for v in test.values):
                return "not_none"
            return None
        if not (
            isinstance(test, ast.Compare)
            and len(test.ops) == 1
            and expr_path(test.left) == var
            and len(test.comparators) == 1
            and isinstance(test.comparators[0], ast.Constant)
            and test.comparators[0].value is None
        ):
            return None
        if isinstance(test.ops[0], ast.IsNot):
            return "not_none"
        if isinstance(test.ops[0], ast.Is):
            return "none"
        return None

    child = node
    for a in ancestors(node):
        if isinstance(a, ast.If):
            kind = _cmp(a.test)
            if kind == "not_none" and _contains(a.body, child):
                return True
            if kind == "none" and _contains(a.orelse, child):
                return True
        child = a
    return False


def _contains(stmts: list[ast.stmt], node: ast.AST) -> bool:
    return any(node is s or node in set(ast.walk(s)) for s in stmts)
