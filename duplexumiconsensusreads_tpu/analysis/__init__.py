"""dutlint: AST-based invariant linter for this codebase.

The paper's core promise — byte-identical duplex consensus output that
survives crashes, faults, and resume — rests on cross-module invariants
that no general-purpose linter knows about: every durable write goes
through ``io.durable``, every phase clock is ``time.monotonic()``,
every ``fault_point`` site is registered and chaos-covered, trace stage
names equal RunReport phase keys, telemetry hooks stay zero-cost when
off. As the streaming executor grew (PR 1-3), these conventions came to
span too many files to police by review alone; this package encodes
them as executable rules.

Layout:
  engine.py  corpus loading (path -> ast), the rule registry, the
             allowlist, and ``run_lint`` — the one entry point
  rules.py   the project's invariant rules (registered on import)
  allowlist.py  intentional, reasoned exceptions (path + rule + reason)
  cli.py     ``tools/dutlint.py`` / the ``dutlint`` console script

Run ``python tools/dutlint.py`` (exit 1 on any non-allowlisted
finding); ``tests/test_lint.py`` runs the same engine in-process as a
tier-1 gate, plus per-rule firing/passing fixtures.
"""

from duplexumiconsensusreads_tpu.analysis.engine import (  # noqa: F401
    Corpus,
    Finding,
    RULES,
    load_corpus,
    run_lint,
)
from duplexumiconsensusreads_tpu.analysis import rules  # noqa: F401  (registers)

__all__ = ["Corpus", "Finding", "RULES", "load_corpus", "run_lint"]
