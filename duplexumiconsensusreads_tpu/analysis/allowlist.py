"""Intentional, audited exceptions to the lint rules.

Policy (ARCHITECTURE.md "Invariants & static analysis"): an entry
suppresses ONE rule in ONE file and must say why the exception is
correct — not why the rule is inconvenient. Entries that no longer
suppress anything are reported by the CLI so the list cannot rot.
Adding an entry is a reviewed change like any other; the default
answer to a finding is to fix the code.
"""

from __future__ import annotations

from duplexumiconsensusreads_tpu.analysis.engine import AllowEntry

ALLOWLIST: tuple[AllowEntry, ...] = (
    AllowEntry(
        rule="durability-protocol",
        path="duplexumiconsensusreads_tpu/io/bam.py",
        reason="write_bam is the whole-file convenience writer used for "
        "simulated/test INPUTS; nothing trusts its output by existence "
        "across a crash, and the streaming executor never calls it",
    ),
    AllowEntry(
        rule="durability-protocol",
        path="duplexumiconsensusreads_tpu/runtime/executor.py",
        reason="write_report emits the diagnostic RunReport JSON: it is "
        "regenerated every run and read by humans/drivers immediately, "
        "never trusted by existence after a crash (and --report - means "
        "stdout, which the protocol cannot wrap)",
    ),
)
