"""The project's invariant rules.

Each rule encodes one convention the correctness story depends on, as
documented in ARCHITECTURE.md "Invariants & static analysis". Rules are
cross-file on purpose: most of these invariants live BETWEEN modules
(a registry here, its call sites there, the test that pins them third),
which is exactly the drift a per-file linter cannot see.

Anchor files are located by path suffix (``Corpus.find``) so the same
rules run over the real tree and over the miniature fixture corpora in
``tests/test_lint.py``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from duplexumiconsensusreads_tpu.analysis.engine import (
    Corpus,
    Finding,
    ancestors,
    call_name,
    enclosing_function,
    guarded_not_none,
    inside_lock_body,
    register,
    str_const,
    str_tuple_assign,
)

# ------------------------------------------------------------- rule: clock

@register(
    "clock-discipline",
    "phase/duration accounting must use time.monotonic(), never time.time()",
)
def check_clock(corpus: Corpus) -> Iterator[Finding]:
    """``RunReport.seconds`` and every duration in the codebase are
    monotonic-clock deltas: an NTP step mid-run must not be able to
    produce negative or inflated phases (see runtime/stream.py's
    accounting and the telemetry epoch). Any ``time.time()`` call is
    therefore suspect — genuine wall-clock needs are allowlisted."""
    for path, tree in corpus.trees.items():
        # names `from time import time [as x]` binds in this module
        aliased = {
            a.asname or a.name
            for node in ast.walk(tree)
            if isinstance(node, ast.ImportFrom) and node.module == "time"
            for a in node.names
            if a.name == "time"
        }
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            hit = (
                isinstance(fn, ast.Attribute)
                and fn.attr == "time"
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "time"
            ) or (isinstance(fn, ast.Name) and fn.id in aliased)
            if hit:
                yield Finding(
                    rule="clock-discipline",
                    path=path,
                    line=node.lineno,
                    message="time.time() used for timing",
                    hint="use time.monotonic() — wall-clock steps (NTP) "
                    "corrupt duration deltas and RunReport.seconds",
                )


# -------------------------------------------------------- rule: durability

_DURABLE_PROTOCOL_CALLS = {"write_durable", "replace_durable", "rewrite_from"}


def _open_write_mode(node: ast.Call) -> bool:
    if call_name(node) != "open" or not isinstance(node.func, ast.Name):
        return False
    mode = None
    if len(node.args) >= 2:
        mode = str_const(node.args[1])
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = str_const(kw.value)
    # '+' catches update modes ("r+b"): an in-place patch of a trusted
    # file is the same torn-write hazard as a fresh write
    return mode is not None and any(c in mode for c in "wax+")


@register(
    "durability-protocol",
    "persistent writes in io//runtime//serve/ must use the "
    "tmp+fsync+rename protocol",
)
def check_durability(corpus: Corpus) -> Iterator[Finding]:
    """A file a later run trusts by existence (shards, manifests, the
    finalised BAM, indexes, the service's queue journal and spooled
    jobs) written with a bare ``open(.., "w")`` can survive a crash
    looking complete while holding torn bytes — the exact failure mode
    io/durable.py exists for. In ``io/``, ``runtime/`` and ``serve/``
    (whose entire crash-recovery story rests on the journal being
    durable), every write-mode open must sit in a function that routes
    through the protocol (write_durable / replace_durable /
    rewrite_from); anything else is a finding (intentional diagnostics
    writers are allowlisted, with reasons)."""
    for path, tree in corpus.trees.items():
        parts = path.split("/")
        if not any(seg in ("io", "runtime", "serve") for seg in parts[:-1]):
            continue
        if path.endswith("io/durable.py"):
            continue  # the protocol implementation itself
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _open_write_mode(node)):
                continue
            fn = enclosing_function(node)
            scope = fn if fn is not None else tree
            uses_protocol = any(
                isinstance(n, ast.Call)
                and call_name(n) in _DURABLE_PROTOCOL_CALLS
                for n in ast.walk(scope)
            )
            if not uses_protocol:
                yield Finding(
                    rule="durability-protocol",
                    path=path,
                    line=node.lineno,
                    message="bare write-mode open() outside the durable "
                    "write protocol",
                    hint="route through io.durable.write_durable (tmp + "
                    "fsync + atomic rename + dir fsync), or stage into a "
                    ".tmp via rewrite_from/replace_durable",
                )


# ----------------------------------------------------- rule: fault registry

@register(
    "fault-registry",
    "fault_point sites, faults.KNOWN_SITES, and the chaos suite must agree",
)
def check_fault_registry(corpus: Corpus) -> Iterator[Finding]:
    """Three-way consistency: (a) every site literal at a
    ``fault_point``/``_io_retry`` call is registered in
    runtime/faults.py KNOWN_SITES (an unregistered site raises at
    runtime — but only when a plan is installed, i.e. exactly when you
    need it); (b) every registered site is actually threaded through
    the code (a dead site gives false chaos-coverage confidence); (c)
    every registered site is exercised by tests/test_chaos.py, either
    as a literal or via a parametrize over KNOWN_SITES."""
    faults_path = corpus.find("runtime/faults.py")
    if faults_path is None:
        return
    known, known_line = str_tuple_assign(
        corpus.trees[faults_path], "KNOWN_SITES"
    )
    if not known:
        yield Finding(
            rule="fault-registry",
            path=faults_path,
            line=1,
            message="KNOWN_SITES literal tuple not found",
            hint="keep KNOWN_SITES a module-level tuple of string literals "
            "so the registry stays statically checkable",
        )
        return
    known_set = set(known)

    # (a) + usage collection: literals at fault_point()/_io_retry() sites
    used: dict[str, tuple[str, int]] = {}
    for path in corpus.package_paths():
        if path == faults_path:
            continue
        for node in ast.walk(corpus.trees[path]):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in ("fault_point", "_io_retry"):
                continue
            if not node.args:
                continue
            site = str_const(node.args[0])
            if site is None:
                continue  # variable site (the fault_point(site) relay)
            used.setdefault(site, (path, node.lineno))
            if site not in known_set:
                yield Finding(
                    rule="fault-registry",
                    path=path,
                    line=node.lineno,
                    message=f"fault site {site!r} is not registered in "
                    f"faults.KNOWN_SITES",
                    hint="add it to KNOWN_SITES (and cover it in "
                    "tests/test_chaos.py) or fix the typo",
                )

    # (b) registered but never threaded through the code
    for site in known:
        if site not in used:
            yield Finding(
                rule="fault-registry",
                path=faults_path,
                line=known_line,
                message=f"KNOWN_SITES entry {site!r} has no "
                f"fault_point/_io_retry call site",
                hint="thread the site through the step it names, or drop "
                "the dead registry entry",
            )

    # (c) chaos coverage. Skipped when the chaos suite isn't in the
    # corpus (explicit-path runs, installed-package runs): the tier-1
    # gate lints the default set, which always anchors it in a checkout
    # (tests/test_lint.py pins that).
    chaos_path = corpus.find("tests/test_chaos.py")
    if chaos_path is None:
        return
    chaos_tree = corpus.trees[chaos_path]
    blanket = any(
        isinstance(node, ast.Call)
        and call_name(node) == "parametrize"
        and any(
            (isinstance(a, ast.Attribute) and a.attr == "KNOWN_SITES")
            or (isinstance(a, ast.Name) and a.id == "KNOWN_SITES")
            for a in node.args
        )
        for node in ast.walk(chaos_tree)
    )
    if blanket:
        return  # a parametrize over the registry covers every site
    # only literals that reach CODE count as coverage — strings inside
    # call arguments (schedule specs, parametrize lists) or assigned
    # data (a BOUNDARY_KILLS-style table later fed to parametrize). A
    # docstring or comment-string mentioning a site must not read as
    # exercising it (docstrings are bare Expr statements: excluded).
    roots: list[ast.AST] = []
    for node in ast.walk(chaos_tree):
        if isinstance(node, ast.Call):
            roots.extend(node.args)
            roots.extend(kw.value for kw in node.keywords)
        elif isinstance(node, ast.Assign):
            roots.append(node.value)
    literals = [
        lit
        for root in roots
        for sub in ast.walk(root)
        if (lit := str_const(sub)) is not None
    ]
    for site in known:
        if not any(site in lit for lit in literals):
            yield Finding(
                rule="fault-registry",
                path=chaos_path,
                line=1,
                message=f"fault site {site!r} is never exercised by the "
                f"chaos suite",
                hint="add a schedule hitting it, or parametrize a test "
                "over faults.KNOWN_SITES",
            )


# ----------------------------------------------------- rule: phase registry

# rep.seconds carries these beside the per-stage busy keys; the golden
# test's key set is the stage set plus exactly these
_DERIVED_SECONDS_KEYS = {"drain_utilization", "total"}


@register(
    "phase-registry",
    "trace stages, DRAIN_PHASES, the phase dict, and the seconds golden "
    "must be one set",
)
def check_phase_registry(corpus: Corpus) -> Iterator[Finding]:
    """The PR-3-era drift class: a stage added to the executor's phase
    dict but not KNOWN_STAGES (the capture validator rejects healthy
    traces), or to both but not the report golden (the driver-facing
    schema silently changes), or a span recorded under a name the sum
    check can't match. One set, four mirrors; this rule diffs them."""
    trace_path = corpus.find("telemetry/trace.py")
    if trace_path is None:
        return
    stages, stages_line = str_tuple_assign(
        corpus.trees[trace_path], "KNOWN_STAGES"
    )
    events, _ = str_tuple_assign(corpus.trees[trace_path], "KNOWN_EVENTS")
    # byte-ledger registry (absent in pre-ledger corpora: the xfer
    # check simply has nothing to pin literals against there)
    xfer_dirs, _ = str_tuple_assign(
        corpus.trees[trace_path], "KNOWN_XFER_DIRS"
    )
    if not stages:
        yield Finding(
            rule="phase-registry",
            path=trace_path,
            line=1,
            message="KNOWN_STAGES literal tuple not found",
            hint="keep KNOWN_STAGES a module-level tuple of string literals",
        )
        return
    stage_set = set(stages)

    # DRAIN_PHASES ⊆ KNOWN_STAGES
    exec_path = corpus.find("runtime/executor.py")
    if exec_path is not None:
        drain, drain_line = str_tuple_assign(
            corpus.trees[exec_path], "DRAIN_PHASES"
        )
        for ph in drain:
            if ph not in stage_set:
                yield Finding(
                    rule="phase-registry",
                    path=exec_path,
                    line=drain_line,
                    message=f"DRAIN_PHASES entry {ph!r} is not a known "
                    f"trace stage",
                    hint="DRAIN_PHASES must be a subset of "
                    "telemetry.trace.KNOWN_STAGES",
                )

    # the streaming executor's phase-accounting dict == KNOWN_STAGES
    stream_path = corpus.find("runtime/stream.py")
    if stream_path is not None:
        phase_keys, phase_line = _phase_dict_keys(corpus.trees[stream_path])
        if phase_keys is not None:
            for k in phase_keys - stage_set:
                yield Finding(
                    rule="phase-registry",
                    path=stream_path,
                    line=phase_line,
                    message=f"phase dict key {k!r} is not a known trace "
                    f"stage",
                    hint="add it to telemetry.trace.KNOWN_STAGES in the "
                    "same change (stage set == phase-key set)",
                )
            for k in stage_set - phase_keys:
                yield Finding(
                    rule="phase-registry",
                    path=stream_path,
                    line=phase_line,
                    message=f"known trace stage {k!r} missing from the "
                    f"phase accounting dict",
                    hint="every stage must accrue busy seconds (the "
                    "trace_report sum-check is phrased over all stages)",
                )

    # every literal span stage / event name recorded in the package is
    # registered (a typo'd stage fails the capture schema check only at
    # runtime, with a trace flag set — exactly too late)
    for path in corpus.package_paths():
        if path == trace_path:
            continue
        for node in ast.walk(corpus.trees[path]):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = call_name(node)
            lit = str_const(node.args[0])
            if lit is None:
                continue
            if name == "span" and lit not in stage_set:
                yield Finding(
                    rule="phase-registry",
                    path=path,
                    line=node.lineno,
                    message=f"span recorded under unknown stage {lit!r}",
                    hint="register the stage in telemetry.trace."
                    "KNOWN_STAGES (and the phase dict + golden)",
                )
            if (
                name in ("event", "emit_event")
                and events
                and lit not in events
            ):
                yield Finding(
                    rule="phase-registry",
                    path=path,
                    line=node.lineno,
                    message=f"event recorded under unknown name {lit!r}",
                    hint="register the event in telemetry.trace.KNOWN_EVENTS",
                )
            if name == "xfer" and xfer_dirs and lit not in xfer_dirs:
                # byte-ledger records: an unregistered direction fails
                # the capture schema only at runtime (wirestat/
                # check_trace exit 1 on a healthy run) — same drift
                # class as a typo'd span stage, same gate
                yield Finding(
                    rule="phase-registry",
                    path=path,
                    line=node.lineno,
                    message=f"xfer recorded under unknown dir {lit!r}",
                    hint="register the direction in telemetry.trace."
                    "KNOWN_XFER_DIRS (and the ledger analysis + "
                    "ARCHITECTURE.md schema)",
                )

    # the RunReport streaming-seconds golden in tests == stages + derived
    golden_path = corpus.find("tests/test_telemetry.py")
    if golden_path is not None:
        golden, golden_line = _golden_seconds_set(corpus.trees[golden_path])
        if golden is not None:
            want = stage_set | _DERIVED_SECONDS_KEYS
            for k in sorted(golden - want):
                yield Finding(
                    rule="phase-registry",
                    path=golden_path,
                    line=golden_line,
                    message=f"seconds-keys golden has {k!r}, which is "
                    f"neither a known stage nor a derived key",
                    hint="stage keys come from telemetry.trace."
                    "KNOWN_STAGES; derived keys are "
                    f"{sorted(_DERIVED_SECONDS_KEYS)}",
                )
            for k in sorted(want - golden):
                yield Finding(
                    rule="phase-registry",
                    path=golden_path,
                    line=golden_line,
                    message=f"seconds-keys golden is missing {k!r}",
                    hint="extend test_streaming_seconds_keys_golden in the "
                    "same change that adds the stage",
                )


def _phase_dict_keys(tree: ast.Module) -> tuple[set[str] | None, int]:
    """Keys of the ``phase = {...}`` accounting dict (all-str-key dict
    literal assigned to a name ``phase``, at any scope)."""
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "phase"
            and isinstance(node.value, ast.Dict)
        ):
            continue
        keys = [str_const(k) for k in node.value.keys if k is not None]
        if keys and all(k is not None for k in keys):
            return {k for k in keys if k is not None}, node.lineno
    return None, 0


def _golden_seconds_set(tree: ast.Module) -> tuple[set[str] | None, int]:
    """The literal set compared in test_streaming_seconds_keys_golden."""
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.FunctionDef)
            and node.name == "test_streaming_seconds_keys_golden"
        ):
            best: tuple[set[str], int] | None = None
            for n in ast.walk(node):
                if isinstance(n, ast.Set):
                    vals = [str_const(e) for e in n.elts]
                    if vals and all(v is not None for v in vals):
                        s = {v for v in vals if v is not None}
                        if best is None or len(s) > len(best[0]):
                            best = (s, n.lineno)
            if best:
                return best
    return None, 0


# ----------------------------------------------------- rule: lock discipline

_BLOCKING_NAMES = {"open", "fsync", "fsync_file", "result", "sleep"}
_MUTATORS = {
    "append", "extend", "insert", "remove", "clear", "update", "pop",
    "popitem", "setdefault", "add", "discard", "appendleft", "popleft",
}


@register(
    "lock-discipline",
    "no blocking I/O inside lock bodies; module-level mutable state "
    "mutated only under a lock",
)
def check_lock_discipline(corpus: Corpus) -> Iterator[Finding]:
    """The executor's locks serialize ACCOUNTING (dict += under
    phase_lock, one buffered line under the recorder lock) — cheap by
    contract. Blocking work inside a lock body (file open, fsync,
    compression, a future's ``result()``, sleep) turns every other
    worker's bookkeeping into convoyed wall time; the pipelined drain's
    whole point evaporates. Scope: runtime/stream.py and
    telemetry/trace.py, the two files whose locks sit on the per-chunk
    hot path. The second check is the inverse: module-level mutable
    containers written OUTSIDE any lock are cross-thread races waiting
    for load (the executor's pools share module state)."""
    for suffix in ("runtime/stream.py", "telemetry/trace.py"):
        path = corpus.find(suffix)
        if path is None:
            continue
        tree = corpus.trees[path]

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            blocking = name in _BLOCKING_NAMES or "compress" in name.lower()
            if blocking and inside_lock_body(node):
                yield Finding(
                    rule="lock-discipline",
                    path=path,
                    line=node.lineno,
                    message=f"blocking call {name}() inside a lock body",
                    hint="do the I/O/compute outside the lock; hold the "
                    "lock only for the shared-state update",
                )

        module_mutables = {
            t.id
            for stmt in tree.body
            if isinstance(stmt, ast.Assign)
            for t in stmt.targets
            if isinstance(t, ast.Name) and _is_mutable_ctor(stmt.value)
        }
        if not module_mutables:
            continue
        for node in ast.walk(tree):
            target_name = _mutated_module_name(node, module_mutables)
            if target_name is None:
                continue
            if enclosing_function(node) is None:
                continue  # module-level init writes are single-threaded
            if not inside_lock_body(node):
                yield Finding(
                    rule="lock-discipline",
                    path=path,
                    line=node.lineno,
                    message=f"module-level mutable {target_name!r} mutated "
                    f"outside any lock",
                    hint="take the module's lock around the mutation (the "
                    "executor's worker pools share this state)",
                )


def _is_mutable_ctor(value: ast.AST) -> bool:
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return True
    return isinstance(value, ast.Call) and call_name(value) in (
        "dict", "list", "set", "deque", "defaultdict", "Counter",
    )


def _mutated_module_name(node: ast.AST, names: set[str]) -> str | None:
    """Name from ``names`` this node mutates, if any: subscript/aug
    assignment or a mutating method call on the bare module-level name."""
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            if (
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name)
                and t.value.id in names
            ):
                return t.value.id
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in _MUTATORS
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id in names
    ):
        return node.func.value.id
    return None


# ----------------------------------------------- rule: lease discipline

@register(
    "lease-discipline",
    "serve/ lease/journal state may only move durably: fenced sites "
    "registered, serving-suite covered, mutations persisted",
)
def check_lease_discipline(corpus: Corpus) -> Iterator[Finding]:
    """The fleet's exactly-once story rests on three conventions that
    drift independently of the generic rules:

    (a) every ``serve.*`` fault-site literal used in ``serve/`` (at
        ``fault_point``/``_io_retry``) is registered in
        ``faults.KNOWN_SITES`` — a typo'd lease site would silently
        skip chaos coverage of a step the takeover proof depends on;
    (b) every registered ``serve.*`` site is exercised by the serving
        suite (``tests/test_serve.py``) AS A LITERAL — the chaos
        blanket parametrize covers transients generically, but the
        lease/fence/expire sites also need the serving-layer kill/
        takeover scenarios, which only live there;
    (c) in ``serve/queue.py``, any function that mutates lease state
        (a ``"lease"``/``"token"`` key assignment, or popping the
        lease) must durably persist in the same function (``save``/
        ``write_durable``) — an in-memory-only lease transition is a
        fleet split-brain the moment two daemons read the journal."""
    faults_path = corpus.find("runtime/faults.py")
    known: set[str] = set()
    if faults_path is not None:
        sites, _ = str_tuple_assign(corpus.trees[faults_path], "KNOWN_SITES")
        known = set(sites)

    # (a) serve.* literals at fault hooks inside serve/ must be registered
    for path, tree in corpus.trees.items():
        parts = path.split("/")
        if "serve" not in parts[:-1]:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            if call_name(node) not in ("fault_point", "_io_retry"):
                continue
            site = str_const(node.args[0])
            if site is None or not site.startswith("serve."):
                continue
            if known and site not in known:
                yield Finding(
                    rule="lease-discipline",
                    path=path,
                    line=node.lineno,
                    message=f"serving fault site {site!r} is not registered "
                    f"in faults.KNOWN_SITES",
                    hint="register it (and cover it in tests/test_serve.py) "
                    "or fix the typo",
                )

    # (b) registered serve.* sites must be serving-suite literals
    serve_anchor = corpus.find("tests/test_serve.py")
    if serve_anchor is not None and known:
        roots: list[ast.AST] = []
        for node in ast.walk(corpus.trees[serve_anchor]):
            if isinstance(node, ast.Call):
                roots.extend(node.args)
                roots.extend(kw.value for kw in node.keywords)
            elif isinstance(node, ast.Assign):
                roots.append(node.value)
        literals = [
            lit
            for root in roots
            for sub in ast.walk(root)
            if (lit := str_const(sub)) is not None
        ]
        for site in sorted(s for s in known if s.startswith("serve.")):
            if not any(site in lit for lit in literals):
                yield Finding(
                    rule="lease-discipline",
                    path=serve_anchor,
                    line=1,
                    message=f"serving fault site {site!r} is never "
                    f"exercised by the serving suite",
                    hint="add a kill/takeover (or registry-pin) case "
                    "naming it in tests/test_serve.py",
                )

    # (c) lease-state mutations in serve/queue.py persist in-function
    queue_path = corpus.find("serve/queue.py")
    if queue_path is not None:
        for fn in ast.walk(corpus.trees[queue_path]):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            line = _lease_mutation_line(fn)
            if line is None:
                continue
            persists = any(
                isinstance(n, ast.Call)
                and (
                    "save" in call_name(n)
                    or call_name(n) in ("write_durable", "replace_durable")
                )
                for n in ast.walk(fn)
            )
            if not persists:
                yield Finding(
                    rule="lease-discipline",
                    path=queue_path,
                    line=line,
                    message=f"lease state mutated in {fn.name}() without a "
                    f"durable persist in the same function",
                    hint="call save() (the journal's durable write) in the "
                    "same transaction that moves lease/token state",
                )


def _lease_mutation_line(fn: ast.AST) -> int | None:
    """First line in ``fn`` that mutates lease state: an assignment
    whose target touches a ``"lease"``/``"token"`` subscript, or a
    ``.pop("lease")`` call."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Subscript) and str_const(
                        sub.slice
                    ) in ("lease", "token"):
                        return node.lineno
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "pop"
            and node.args
            and str_const(node.args[0]) == "lease"
        ):
            return node.lineno
    return None


# ------------------------------------------------ rule: deadline discipline

# the time-stamp key family in the serving journal: anything from it
# must carry the domain suffix — "_m" (a time.monotonic() reading) or
# "_s" (a duration). A bare "deadline"/"expires" key is exactly the
# place a wall-clock reading sneaks in and survives review, because it
# works until the first NTP step (or cross-restart comparison) voids
# every expiry at once.
_STAMP_KEY_PREFIXES = ("deadline", "expires", "progress", "admitted",
                       "claimed")


@register(
    "deadline-discipline",
    "serve/ deadlines/expiries live in the monotonic domain; every "
    "journal state literal is registered and serving-suite exercised",
)
def check_deadline_discipline(corpus: Corpus) -> Iterator[Finding]:
    """Three checks, all cheap to drift past review:

    (a) STAMP-KEY NAMING: in ``serve/``, any journal key from the
        time-stamp family (deadline/expires/progress/admitted/claimed)
        must end in ``_m`` (monotonic stamp) or ``_s`` (duration) —
        the naming convention IS the domain annotation the clock rule
        cannot see across a dict boundary;
    (b) MONOTONIC DERIVATION: a function that writes a ``*_m`` key
        must read ``time.monotonic()`` in the same scope — a ``*_m``
        key fed from anything else is a lie wearing the convention;
    (c) STATE REGISTRY: every literal a ``serve/`` file assigns into a
        journal entry's ``state`` is registered in
        ``serve/queue.py`` JOB_STATES, and every registered state is
        exercised by ``tests/test_serve.py`` as a literal — an
        unregistered terminal state (expired, quarantined, ...) would
        silently fall out of compaction/idle/status logic."""
    serve_paths = [
        p for p in corpus.trees
        if "serve" in p.split("/")[:-1]
    ]

    # (a) stamp-key naming
    for path in serve_paths:
        for lit, line in _dict_key_literals(corpus.trees[path]):
            if not lit.startswith(_STAMP_KEY_PREFIXES):
                continue
            if lit.endswith(("_m", "_s")):
                continue
            yield Finding(
                rule="deadline-discipline",
                path=path,
                line=line,
                message=f"time-stamp key {lit!r} without a clock-domain "
                f"suffix",
                hint="name monotonic stamps '<key>_m' and durations "
                "'<key>_s' — the suffix is the domain annotation the "
                "deadline arithmetic is checked against",
            )

    # (b) *_m keys must be derived from time.monotonic() in-function
    for path in serve_paths:
        for fn in ast.walk(corpus.trees[path]):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            line = _monotonic_stamp_assign_line(fn)
            if line is None:
                continue
            mentions = any(
                (isinstance(n, ast.Attribute) and n.attr == "monotonic")
                or (isinstance(n, ast.Name) and "monotonic" in n.id)
                for n in ast.walk(fn)
            )
            if not mentions:
                yield Finding(
                    rule="deadline-discipline",
                    path=path,
                    line=line,
                    message=f"monotonic-domain key written in {fn.name}() "
                    f"without a time.monotonic() reading in scope",
                    hint="compute *_m stamps from time.monotonic() in the "
                    "same function (wall clocks void every expiry on an "
                    "NTP step)",
                )

    # (c) state-literal registry + serving-suite exercise
    queue_path = corpus.find("serve/queue.py")
    if queue_path is None:
        return
    states, states_line = str_tuple_assign(
        corpus.trees[queue_path], "JOB_STATES"
    )
    if not states:
        yield Finding(
            rule="deadline-discipline",
            path=queue_path,
            line=1,
            message="JOB_STATES literal tuple not found",
            hint="keep JOB_STATES a module-level tuple of string literals "
            "so the state machine stays statically checkable",
        )
        return
    state_set = set(states)
    for path in serve_paths:
        for lit, line in _journal_state_literals(corpus.trees[path]):
            if lit not in state_set:
                yield Finding(
                    rule="deadline-discipline",
                    path=path,
                    line=line,
                    message=f"journal state literal {lit!r} is not "
                    f"registered in serve.queue.JOB_STATES",
                    hint="register the state (and cover it in "
                    "tests/test_serve.py) or fix the typo",
                )
    serve_anchor = corpus.find("tests/test_serve.py")
    if serve_anchor is None:
        return
    roots: list[ast.AST] = []
    for node in ast.walk(corpus.trees[serve_anchor]):
        if isinstance(node, ast.Call):
            roots.extend(node.args)
            roots.extend(kw.value for kw in node.keywords)
        elif isinstance(node, ast.Assign):
            roots.append(node.value)
        elif isinstance(node, ast.Compare):
            # `assert status["state"] == "expired"` is the natural way
            # a test exercises a state — comparisons count, docstrings
            # still don't (bare Expr statements are never roots)
            roots.extend(node.comparators)
    literals = [
        lit
        for root in roots
        for sub in ast.walk(root)
        if (lit := str_const(sub)) is not None
    ]
    for state in states:
        if not any(state in lit for lit in literals):
            yield Finding(
                rule="deadline-discipline",
                path=serve_anchor,
                line=1,
                message=f"journal state {state!r} is never exercised by "
                f"the serving suite",
                hint="add a test driving a job through it (or a "
                "registry-pin naming it) in tests/test_serve.py",
            )


def _dict_key_literals(tree: ast.Module) -> Iterator[tuple[str, int]]:
    """String literals in DICT-KEY position: dict-literal keys,
    subscript slices, and the key arg of .get/.pop/.setdefault — the
    places a journal field name can appear."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if k is None:
                    continue
                s = str_const(k)
                if s is not None:
                    yield s, k.lineno
        elif isinstance(node, ast.Subscript):
            s = str_const(node.slice)
            if s is not None:
                yield s, node.lineno
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("get", "pop", "setdefault")
            and node.args
        ):
            s = str_const(node.args[0])
            if s is not None:
                yield s, node.lineno


def _monotonic_stamp_assign_line(fn: ast.AST) -> int | None:
    """First line in ``fn`` assigning a subscript whose literal key
    ends in ``_m`` (a monotonic stamp write)."""
    for node in ast.walk(fn):
        if not isinstance(node, (ast.Assign, ast.AugAssign)):
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Subscript):
                    s = str_const(sub.slice)
                    if s is not None and s.endswith("_m"):
                        return node.lineno
    return None


def _journal_state_literals(tree: ast.Module) -> Iterator[tuple[str, int]]:
    """State literals written INTO journal entries: ``<x>["state"] =
    "lit"`` subscript assignments, and the ``"state"`` value of any
    dict literal ASSIGNED to a name or into a container (which covers
    both ``self.jobs[id] = {"state": ...}`` and the temporary-dict
    pattern ``entry = {"state": ...}; self.jobs[id] = entry``).
    Read-side literals built inline in ``return`` expressions (status
    rendering, client pseudo-states like "submitted") are not journal
    writes and are deliberately out of scope."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if (
                isinstance(t, ast.Subscript)
                and str_const(t.slice) == "state"
            ):
                s = str_const(node.value)
                if s is not None:
                    yield s, node.lineno
        if isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                if k is not None and str_const(k) == "state":
                    s = str_const(v)
                    if s is not None:
                        yield s, node.lineno


# --------------------------------------------------------- rule: hook guard

@register(
    "hook-guard",
    "recorder span/event/xfer hooks on hot paths must be behind a "
    "single None check",
)
def check_hook_guard(corpus: Corpus) -> Iterator[Finding]:
    """The zero-cost-when-off contract (same discipline as
    ``faults.fault_point``): with tracing off, every telemetry hook in
    the per-chunk path must cost one None check — so a direct
    ``tr.span(...)`` / ``tr.event(...)`` / ``tr.xfer(...)`` on a local
    recorder variable must sit inside ``if tr is not None:`` (or the
    ``else`` of ``if tr is None:``) — dotted receivers (``ctx.tr.span``,
    ``self._recorder.event``) included, guarded on the same dotted
    path. Module-hook helpers (``emit_event``, ``fault_point``) carry
    the check internally and are exempt; a bare ``self.span(...)`` is
    the recorder's own internals and is exempt too."""
    from duplexumiconsensusreads_tpu.analysis.engine import expr_path

    for path in corpus.package_paths():
        tree = corpus.trees.get(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in ("span", "event", "xfer")
            ):
                continue
            var = expr_path(fn.value)
            if var is None or var == "self":
                continue  # no stable identity / recorder internals
            if not guarded_not_none(node, var):
                yield Finding(
                    rule="hook-guard",
                    path=path,
                    line=node.lineno,
                    message=f"unguarded telemetry hook {var}.{fn.attr}(...)",
                    hint=f"wrap in `if {var} is not None:` — hooks must be "
                    "a single None check when tracing is off",
                )
