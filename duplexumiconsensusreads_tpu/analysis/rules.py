"""The project's invariant rules.

Each rule encodes one convention the correctness story depends on, as
documented in ARCHITECTURE.md "Invariants & static analysis". Rules are
cross-file on purpose: most of these invariants live BETWEEN modules
(a registry here, its call sites there, the test that pins them third),
which is exactly the drift a per-file linter cannot see.

Anchor files are located by path suffix (``Corpus.find``) so the same
rules run over the real tree and over the miniature fixture corpora in
``tests/test_lint.py``.
"""

from __future__ import annotations

import ast
from typing import Iterator

from duplexumiconsensusreads_tpu.analysis.engine import (
    Corpus,
    Finding,
    ancestors,
    call_name,
    enclosing_function,
    expr_path,
    function_defs,
    guarded_not_none,
    inside_lock_body,
    inside_named_lock,
    literal_assign,
    reachable_functions,
    register,
    str_const,
    str_dict_assign,
    str_tuple_assign,
)

# ------------------------------------------------------------- rule: clock

@register(
    "clock-discipline",
    "phase/duration accounting must use time.monotonic(), never time.time()",
)
def check_clock(corpus: Corpus) -> Iterator[Finding]:
    """``RunReport.seconds`` and every duration in the codebase are
    monotonic-clock deltas: an NTP step mid-run must not be able to
    produce negative or inflated phases (see runtime/stream.py's
    accounting and the telemetry epoch). Any ``time.time()`` call is
    therefore suspect — genuine wall-clock needs are allowlisted."""
    for path, tree in corpus.trees.items():
        # names `from time import time [as x]` binds in this module
        aliased = {
            a.asname or a.name
            for node in ast.walk(tree)
            if isinstance(node, ast.ImportFrom) and node.module == "time"
            for a in node.names
            if a.name == "time"
        }
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            hit = (
                isinstance(fn, ast.Attribute)
                and fn.attr == "time"
                and isinstance(fn.value, ast.Name)
                and fn.value.id == "time"
            ) or (isinstance(fn, ast.Name) and fn.id in aliased)
            if hit:
                yield Finding(
                    rule="clock-discipline",
                    path=path,
                    line=node.lineno,
                    message="time.time() used for timing",
                    hint="use time.monotonic() — wall-clock steps (NTP) "
                    "corrupt duration deltas and RunReport.seconds",
                )


# -------------------------------------------------------- rule: durability

_DURABLE_PROTOCOL_CALLS = {"write_durable", "replace_durable", "rewrite_from"}


def _open_write_mode(node: ast.Call) -> bool:
    if call_name(node) != "open" or not isinstance(node.func, ast.Name):
        return False
    mode = None
    if len(node.args) >= 2:
        mode = str_const(node.args[1])
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = str_const(kw.value)
    # '+' catches update modes ("r+b"): an in-place patch of a trusted
    # file is the same torn-write hazard as a fresh write
    return mode is not None and any(c in mode for c in "wax+")


@register(
    "durability-protocol",
    "persistent writes in io//runtime//serve/ must use the "
    "tmp+fsync+rename protocol",
)
def check_durability(corpus: Corpus) -> Iterator[Finding]:
    """A file a later run trusts by existence (shards, manifests, the
    finalised BAM, indexes, the service's queue journal and spooled
    jobs) written with a bare ``open(.., "w")`` can survive a crash
    looking complete while holding torn bytes — the exact failure mode
    io/durable.py exists for. In ``io/``, ``runtime/`` and ``serve/``
    (whose entire crash-recovery story rests on the journal being
    durable), every write-mode open must sit in a function that routes
    through the protocol (write_durable / replace_durable /
    rewrite_from); anything else is a finding (intentional diagnostics
    writers are allowlisted, with reasons)."""
    for path, tree in corpus.trees.items():
        parts = path.split("/")
        if not any(seg in ("io", "runtime", "serve") for seg in parts[:-1]):
            continue
        if path.endswith("io/durable.py"):
            continue  # the protocol implementation itself
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and _open_write_mode(node)):
                continue
            fn = enclosing_function(node)
            scope = fn if fn is not None else tree
            uses_protocol = any(
                isinstance(n, ast.Call)
                and call_name(n) in _DURABLE_PROTOCOL_CALLS
                for n in ast.walk(scope)
            )
            if not uses_protocol:
                yield Finding(
                    rule="durability-protocol",
                    path=path,
                    line=node.lineno,
                    message="bare write-mode open() outside the durable "
                    "write protocol",
                    hint="route through io.durable.write_durable (tmp + "
                    "fsync + atomic rename + dir fsync), or stage into a "
                    ".tmp via rewrite_from/replace_durable",
                )


# ----------------------------------------------------- rule: fault registry

@register(
    "fault-registry",
    "fault_point sites, faults.KNOWN_SITES, and the chaos suite must agree",
)
def check_fault_registry(corpus: Corpus) -> Iterator[Finding]:
    """Three-way consistency: (a) every site literal at a
    ``fault_point``/``_io_retry`` call is registered in
    runtime/faults.py KNOWN_SITES (an unregistered site raises at
    runtime — but only when a plan is installed, i.e. exactly when you
    need it); (b) every registered site is actually threaded through
    the code (a dead site gives false chaos-coverage confidence); (c)
    every registered site is exercised by tests/test_chaos.py, either
    as a literal or via a parametrize over KNOWN_SITES."""
    faults_path = corpus.find("runtime/faults.py")
    if faults_path is None:
        return
    known, known_line = str_tuple_assign(
        corpus.trees[faults_path], "KNOWN_SITES"
    )
    if not known:
        yield Finding(
            rule="fault-registry",
            path=faults_path,
            line=1,
            message="KNOWN_SITES literal tuple not found",
            hint="keep KNOWN_SITES a module-level tuple of string literals "
            "so the registry stays statically checkable",
        )
        return
    known_set = set(known)

    # (a) + usage collection: literals at fault_point()/_io_retry() sites
    used: dict[str, tuple[str, int]] = {}
    for path in corpus.package_paths():
        if path == faults_path:
            continue
        for node in ast.walk(corpus.trees[path]):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in ("fault_point", "_io_retry"):
                continue
            if not node.args:
                continue
            site = str_const(node.args[0])
            if site is None:
                continue  # variable site (the fault_point(site) relay)
            used.setdefault(site, (path, node.lineno))
            if site not in known_set:
                yield Finding(
                    rule="fault-registry",
                    path=path,
                    line=node.lineno,
                    message=f"fault site {site!r} is not registered in "
                    f"faults.KNOWN_SITES",
                    hint="add it to KNOWN_SITES (and cover it in "
                    "tests/test_chaos.py) or fix the typo",
                )

    # (b) registered but never threaded through the code
    for site in known:
        if site not in used:
            yield Finding(
                rule="fault-registry",
                path=faults_path,
                line=known_line,
                message=f"KNOWN_SITES entry {site!r} has no "
                f"fault_point/_io_retry call site",
                hint="thread the site through the step it names, or drop "
                "the dead registry entry",
            )

    # (c) chaos coverage. Skipped when the chaos suite isn't in the
    # corpus (explicit-path runs, installed-package runs): the tier-1
    # gate lints the default set, which always anchors it in a checkout
    # (tests/test_lint.py pins that).
    chaos_path = corpus.find("tests/test_chaos.py")
    if chaos_path is None:
        return
    chaos_tree = corpus.trees[chaos_path]
    blanket = any(
        isinstance(node, ast.Call)
        and call_name(node) == "parametrize"
        and any(
            (isinstance(a, ast.Attribute) and a.attr == "KNOWN_SITES")
            or (isinstance(a, ast.Name) and a.id == "KNOWN_SITES")
            for a in node.args
        )
        for node in ast.walk(chaos_tree)
    )
    if blanket:
        return  # a parametrize over the registry covers every site
    # only literals that reach CODE count as coverage — strings inside
    # call arguments (schedule specs, parametrize lists) or assigned
    # data (a BOUNDARY_KILLS-style table later fed to parametrize). A
    # docstring or comment-string mentioning a site must not read as
    # exercising it (docstrings are bare Expr statements: excluded).
    roots: list[ast.AST] = []
    for node in ast.walk(chaos_tree):
        if isinstance(node, ast.Call):
            roots.extend(node.args)
            roots.extend(kw.value for kw in node.keywords)
        elif isinstance(node, ast.Assign):
            roots.append(node.value)
    literals = [
        lit
        for root in roots
        for sub in ast.walk(root)
        if (lit := str_const(sub)) is not None
    ]
    for site in known:
        if not any(site in lit for lit in literals):
            yield Finding(
                rule="fault-registry",
                path=chaos_path,
                line=1,
                message=f"fault site {site!r} is never exercised by the "
                f"chaos suite",
                hint="add a schedule hitting it, or parametrize a test "
                "over faults.KNOWN_SITES",
            )


# ----------------------------------------------------- rule: phase registry

# rep.seconds carries these beside the per-stage busy keys; the golden
# test's key set is the stage set plus exactly these
_DERIVED_SECONDS_KEYS = {"drain_utilization", "total"}


@register(
    "phase-registry",
    "trace stages, DRAIN_PHASES, the phase dict, and the seconds golden "
    "must be one set",
)
def check_phase_registry(corpus: Corpus) -> Iterator[Finding]:
    """The PR-3-era drift class: a stage added to the executor's phase
    dict but not KNOWN_STAGES (the capture validator rejects healthy
    traces), or to both but not the report golden (the driver-facing
    schema silently changes), or a span recorded under a name the sum
    check can't match. One set, four mirrors; this rule diffs them."""
    trace_path = corpus.find("telemetry/trace.py")
    if trace_path is None:
        return
    stages, stages_line = str_tuple_assign(
        corpus.trees[trace_path], "KNOWN_STAGES"
    )
    events, _ = str_tuple_assign(corpus.trees[trace_path], "KNOWN_EVENTS")
    # byte-ledger registry (absent in pre-ledger corpora: the xfer
    # check simply has nothing to pin literals against there)
    xfer_dirs, _ = str_tuple_assign(
        corpus.trees[trace_path], "KNOWN_XFER_DIRS"
    )
    # h2d ledger-record attr registry (the bucket-tuner's fill-factor
    # audit fields ride h2d records; absent in pre-tuner corpora)
    h2d_attrs, _ = str_tuple_assign(
        corpus.trees[trace_path], "KNOWN_H2D_XFER_ATTRS"
    )
    # literal-lane registry (mesh execution's dev-N device lanes, the
    # service's job-<id> lanes; absent in pre-mesh corpora, where the
    # lane check simply skips)
    lane_prefixes, _ = str_tuple_assign(
        corpus.trees[trace_path], "KNOWN_LANE_PREFIXES"
    )
    # fleet timeline registries (telemetry/fleet.py): segment/gap kinds
    # the cross-daemon stitcher constructs and the SLO/prom surfaces key
    # on — absent in pre-fleet corpora, where the checks simply skip
    fleet_path = corpus.find("telemetry/fleet.py")
    seg_kinds: list[str] = []
    gap_kinds: list[str] = []
    fleet_reg_line = 1
    if fleet_path is not None:
        seg_kinds, fleet_reg_line = str_tuple_assign(
            corpus.trees[fleet_path], "FLEET_SEGMENT_KINDS"
        )
        gap_kinds, _ = str_tuple_assign(
            corpus.trees[fleet_path], "FLEET_GAP_KINDS"
        )
    if not stages:
        yield Finding(
            rule="phase-registry",
            path=trace_path,
            line=1,
            message="KNOWN_STAGES literal tuple not found",
            hint="keep KNOWN_STAGES a module-level tuple of string literals",
        )
        return
    stage_set = set(stages)

    # DRAIN_PHASES ⊆ KNOWN_STAGES
    exec_path = corpus.find("runtime/executor.py")
    if exec_path is not None:
        drain, drain_line = str_tuple_assign(
            corpus.trees[exec_path], "DRAIN_PHASES"
        )
        for ph in drain:
            if ph not in stage_set:
                yield Finding(
                    rule="phase-registry",
                    path=exec_path,
                    line=drain_line,
                    message=f"DRAIN_PHASES entry {ph!r} is not a known "
                    f"trace stage",
                    hint="DRAIN_PHASES must be a subset of "
                    "telemetry.trace.KNOWN_STAGES",
                )

    # the streaming executor's phase-accounting dict == KNOWN_STAGES
    stream_path = corpus.find("runtime/stream.py")
    if stream_path is not None:
        phase_keys, phase_line = _phase_dict_keys(corpus.trees[stream_path])
        if phase_keys is not None:
            for k in phase_keys - stage_set:
                yield Finding(
                    rule="phase-registry",
                    path=stream_path,
                    line=phase_line,
                    message=f"phase dict key {k!r} is not a known trace "
                    f"stage",
                    hint="add it to telemetry.trace.KNOWN_STAGES in the "
                    "same change (stage set == phase-key set)",
                )
            for k in stage_set - phase_keys:
                yield Finding(
                    rule="phase-registry",
                    path=stream_path,
                    line=phase_line,
                    message=f"known trace stage {k!r} missing from the "
                    f"phase accounting dict",
                    hint="every stage must accrue busy seconds (the "
                    "trace_report sum-check is phrased over all stages)",
                )

    # every literal span stage / event name recorded in the package is
    # registered (a typo'd stage fails the capture schema check only at
    # runtime, with a trace flag set — exactly too late)
    for path in corpus.package_paths():
        if path == trace_path:
            continue
        for node in ast.walk(corpus.trees[path]):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = call_name(node)
            lit = str_const(node.args[0])
            if lit is None:
                continue
            if name == "span" and lit not in stage_set:
                yield Finding(
                    rule="phase-registry",
                    path=path,
                    line=node.lineno,
                    message=f"span recorded under unknown stage {lit!r}",
                    hint="register the stage in telemetry.trace."
                    "KNOWN_STAGES (and the phase dict + golden)",
                )
            if (
                name in ("event", "emit_event")
                and events
                and lit not in events
            ):
                yield Finding(
                    rule="phase-registry",
                    path=path,
                    line=node.lineno,
                    message=f"event recorded under unknown name {lit!r}",
                    hint="register the event in telemetry.trace.KNOWN_EVENTS",
                )
            if name == "xfer" and xfer_dirs and lit not in xfer_dirs:
                # byte-ledger records: an unregistered direction fails
                # the capture schema only at runtime (wirestat/
                # check_trace exit 1 on a healthy run) — same drift
                # class as a typo'd span stage, same gate
                yield Finding(
                    rule="phase-registry",
                    path=path,
                    line=node.lineno,
                    message=f"xfer recorded under unknown dir {lit!r}",
                    hint="register the direction in telemetry.trace."
                    "KNOWN_XFER_DIRS (and the ledger analysis + "
                    "ARCHITECTURE.md schema)",
                )
            if name == "seg_rec" and seg_kinds and lit not in seg_kinds:
                # fleet timeline records: an unregistered segment kind
                # forks the stitched-timeline schema the SLO gates and
                # the Perfetto export key on — same drift class as a
                # typo'd span stage (the constructor also refuses at
                # runtime; this catches it at lint time)
                yield Finding(
                    rule="phase-registry",
                    path=path,
                    line=node.lineno,
                    message=f"fleet segment recorded under unknown kind "
                    f"{lit!r}",
                    hint="register the kind in telemetry.fleet."
                    "FLEET_SEGMENT_KINDS (and ARCHITECTURE.md's fleet "
                    "observability schema)",
                )
            if name == "gap_rec" and gap_kinds and lit not in gap_kinds:
                yield Finding(
                    rule="phase-registry",
                    path=path,
                    line=node.lineno,
                    message=f"fleet gap recorded under unknown kind "
                    f"{lit!r}",
                    hint="register the kind in telemetry.fleet."
                    "FLEET_GAP_KINDS (and ARCHITECTURE.md's fleet "
                    "observability schema)",
                )
            if name in ("span", "event", "emit_event", "xfer") and lane_prefixes:
                # literal lane families must be registered: a typo'd
                # lane= ("gpu-0", f"chip{i}") silently forks the
                # grouping key wirestat's device table, the fleet
                # stitcher and the chrome export all key on. Dynamic
                # lanes (current_lane(), a variable) are thread-derived
                # and stay out of scope; an f-string is checked by its
                # leading literal, so a placeholder-first lane is
                # unpinnable and flagged too.
                for kw in node.keywords or ():
                    if kw.arg != "lane":
                        continue
                    head = _lane_head(kw.value)
                    if head is None:
                        continue
                    ok = head == "main" or any(
                        p.endswith("-") and head.startswith(p)
                        for p in lane_prefixes
                    )
                    if not ok:
                        yield Finding(
                            rule="phase-registry",
                            path=path,
                            line=node.lineno,
                            message=f"literal lane {head!r}... is not "
                            f"registered",
                            hint="lane literals must be 'main' or start "
                            "with a telemetry.trace.KNOWN_LANE_PREFIXES "
                            "entry (dev-/job-/...)",
                        )
            if name == "xfer" and lit == "h2d" and h2d_attrs:
                # h2d records carry the packing/fill audit attrs; an
                # unregistered keyword is a silent schema fork — the
                # xfer envelope golden and wirestat's fill reader both
                # key on the registered set
                for kw in node.keywords or ():
                    if kw.arg in (None, "chunk", "lane", "resumed"):
                        continue
                    if kw.arg not in h2d_attrs:
                        yield Finding(
                            rule="phase-registry",
                            path=path,
                            line=node.lineno,
                            message=f"h2d xfer attr {kw.arg!r} is not "
                            f"registered",
                            hint="register it in telemetry.trace."
                            "KNOWN_H2D_XFER_ATTRS (and the xfer schema "
                            "golden + ARCHITECTURE.md)",
                        )

    # dead-registry detection, the fault-registry rule's second
    # direction: a fleet kind nothing in the stitcher ever produces is
    # a schema entry consumers will wait on forever. Literals inside
    # the registry tuples themselves don't count as use.
    if fleet_path is not None and (seg_kinds or gap_kinds):
        skip_nodes = set()
        for node in ast.walk(corpus.trees[fleet_path]):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id in (
                    "FLEET_SEGMENT_KINDS", "FLEET_GAP_KINDS"
                )
            ):
                skip_nodes.update(id(n) for n in ast.walk(node))
        used = {
            lit
            for node in ast.walk(corpus.trees[fleet_path])
            if id(node) not in skip_nodes
            and (lit := str_const(node)) is not None
        }
        for kind in list(seg_kinds) + list(gap_kinds):
            if kind not in used:
                yield Finding(
                    rule="phase-registry",
                    path=fleet_path,
                    line=fleet_reg_line,
                    message=f"fleet kind {kind!r} is registered but the "
                    f"stitcher never produces it",
                    hint="emit it in telemetry/fleet.py or prune the "
                    "registry entry",
                )

    # the RunReport streaming-seconds golden in tests == stages + derived
    golden_path = corpus.find("tests/test_telemetry.py")
    if golden_path is not None:
        golden, golden_line = _golden_seconds_set(corpus.trees[golden_path])
        if golden is not None:
            want = stage_set | _DERIVED_SECONDS_KEYS
            for k in sorted(golden - want):
                yield Finding(
                    rule="phase-registry",
                    path=golden_path,
                    line=golden_line,
                    message=f"seconds-keys golden has {k!r}, which is "
                    f"neither a known stage nor a derived key",
                    hint="stage keys come from telemetry.trace."
                    "KNOWN_STAGES; derived keys are "
                    f"{sorted(_DERIVED_SECONDS_KEYS)}",
                )
            for k in sorted(want - golden):
                yield Finding(
                    rule="phase-registry",
                    path=golden_path,
                    line=golden_line,
                    message=f"seconds-keys golden is missing {k!r}",
                    hint="extend test_streaming_seconds_keys_golden in the "
                    "same change that adds the stage",
                )


def _lane_head(v) -> str | None:
    """Leading literal of a ``lane=`` argument: the full string for a
    plain literal, the pre-placeholder prefix for an f-string (""
    when the f-string STARTS with a placeholder — an unpinnable lane
    family, flagged), None for dynamic expressions (thread-derived
    lanes like ``current_lane()`` or a variable — out of scope)."""
    lit = str_const(v)
    if lit is not None:
        return lit
    if isinstance(v, ast.JoinedStr):
        if (
            v.values
            and isinstance(v.values[0], ast.Constant)
            and isinstance(v.values[0].value, str)
        ):
            return v.values[0].value
        return ""
    return None


def _phase_dict_keys(tree: ast.Module) -> tuple[set[str] | None, int]:
    """Keys of the ``phase = {...}`` accounting dict (all-str-key dict
    literal assigned to a name ``phase``, at any scope)."""
    for node in ast.walk(tree):
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == "phase"
            and isinstance(node.value, ast.Dict)
        ):
            continue
        keys = [str_const(k) for k in node.value.keys if k is not None]
        if keys and all(k is not None for k in keys):
            return {k for k in keys if k is not None}, node.lineno
    return None, 0


def _golden_seconds_set(tree: ast.Module) -> tuple[set[str] | None, int]:
    """The literal set compared in test_streaming_seconds_keys_golden."""
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.FunctionDef)
            and node.name == "test_streaming_seconds_keys_golden"
        ):
            best: tuple[set[str], int] | None = None
            for n in ast.walk(node):
                if isinstance(n, ast.Set):
                    vals = [str_const(e) for e in n.elts]
                    if vals and all(v is not None for v in vals):
                        s = {v for v in vals if v is not None}
                        if best is None or len(s) > len(best[0]):
                            best = (s, n.lineno)
            if best:
                return best
    return None, 0


# ----------------------------------------------------- rule: lock discipline

_BLOCKING_NAMES = {"open", "fsync", "fsync_file", "result", "sleep"}
_MUTATORS = {
    "append", "extend", "insert", "remove", "clear", "update", "pop",
    "popitem", "setdefault", "add", "discard", "appendleft", "popleft",
}


@register(
    "lock-discipline",
    "no blocking I/O inside lock bodies; module-level mutable state "
    "mutated only under a lock",
)
def check_lock_discipline(corpus: Corpus) -> Iterator[Finding]:
    """The executor's locks serialize ACCOUNTING (dict += under
    phase_lock, one buffered line under the recorder lock) — cheap by
    contract. Blocking work inside a lock body (file open, fsync,
    compression, a future's ``result()``, sleep) turns every other
    worker's bookkeeping into convoyed wall time; the pipelined drain's
    whole point evaporates. Scope: runtime/stream.py and
    telemetry/trace.py, the two files whose locks sit on the per-chunk
    hot path. The second check is the inverse: module-level mutable
    containers written OUTSIDE any lock are cross-thread races waiting
    for load (the executor's pools share module state)."""
    for suffix in ("runtime/stream.py", "telemetry/trace.py"):
        path = corpus.find(suffix)
        if path is None:
            continue
        tree = corpus.trees[path]

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            blocking = name in _BLOCKING_NAMES or "compress" in name.lower()
            if blocking and inside_lock_body(node):
                yield Finding(
                    rule="lock-discipline",
                    path=path,
                    line=node.lineno,
                    message=f"blocking call {name}() inside a lock body",
                    hint="do the I/O/compute outside the lock; hold the "
                    "lock only for the shared-state update",
                )

        module_mutables = {
            t.id
            for stmt in tree.body
            if isinstance(stmt, ast.Assign)
            for t in stmt.targets
            if isinstance(t, ast.Name) and _is_mutable_ctor(stmt.value)
        }
        if not module_mutables:
            continue
        for node in ast.walk(tree):
            target_name = _mutated_module_name(node, module_mutables)
            if target_name is None:
                continue
            if enclosing_function(node) is None:
                continue  # module-level init writes are single-threaded
            if not inside_lock_body(node):
                yield Finding(
                    rule="lock-discipline",
                    path=path,
                    line=node.lineno,
                    message=f"module-level mutable {target_name!r} mutated "
                    f"outside any lock",
                    hint="take the module's lock around the mutation (the "
                    "executor's worker pools share this state)",
                )


def _is_mutable_ctor(value: ast.AST) -> bool:
    if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
        return True
    return isinstance(value, ast.Call) and call_name(value) in (
        "dict", "list", "set", "deque", "defaultdict", "Counter",
    )


def _mutated_module_name(node: ast.AST, names: set[str]) -> str | None:
    """Name from ``names`` this node mutates, if any: subscript/aug
    assignment or a mutating method call on the bare module-level name."""
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        for t in targets:
            if (
                isinstance(t, ast.Subscript)
                and isinstance(t.value, ast.Name)
                and t.value.id in names
            ):
                return t.value.id
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in _MUTATORS
        and isinstance(node.func.value, ast.Name)
        and node.func.value.id in names
    ):
        return node.func.value.id
    return None


# ----------------------------------------------- rule: lease discipline

@register(
    "lease-discipline",
    "serve/ lease/journal state may only move durably: fenced sites "
    "registered, serving-suite covered, mutations persisted",
)
def check_lease_discipline(corpus: Corpus) -> Iterator[Finding]:
    """The fleet's exactly-once story rests on three conventions that
    drift independently of the generic rules:

    (a) every ``serve.*`` fault-site literal used in ``serve/`` (at
        ``fault_point``/``_io_retry``) is registered in
        ``faults.KNOWN_SITES`` — a typo'd lease site would silently
        skip chaos coverage of a step the takeover proof depends on;
    (b) every registered ``serve.*`` site is exercised by the serving
        suite (``tests/test_serve.py``) AS A LITERAL — the chaos
        blanket parametrize covers transients generically, but the
        lease/fence/expire sites also need the serving-layer kill/
        takeover scenarios, which only live there;
    (c) in ``serve/queue.py``, any function that mutates lease state
        (a ``"lease"``/``"token"`` key assignment, or popping the
        lease) must durably persist in the same function (``save``/
        ``write_durable``) — an in-memory-only lease transition is a
        fleet split-brain the moment two daemons read the journal."""
    faults_path = corpus.find("runtime/faults.py")
    known: set[str] = set()
    if faults_path is not None:
        sites, _ = str_tuple_assign(corpus.trees[faults_path], "KNOWN_SITES")
        known = set(sites)

    # (a) serve.* literals at fault hooks inside serve/ must be registered
    for path, tree in corpus.trees.items():
        parts = path.split("/")
        if "serve" not in parts[:-1]:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            if call_name(node) not in ("fault_point", "_io_retry"):
                continue
            site = str_const(node.args[0])
            if site is None or not site.startswith("serve."):
                continue
            if known and site not in known:
                yield Finding(
                    rule="lease-discipline",
                    path=path,
                    line=node.lineno,
                    message=f"serving fault site {site!r} is not registered "
                    f"in faults.KNOWN_SITES",
                    hint="register it (and cover it in tests/test_serve.py) "
                    "or fix the typo",
                )

    # (b) registered serve.* sites must be serving-suite literals
    serve_anchor = corpus.find("tests/test_serve.py")
    if serve_anchor is not None and known:
        roots: list[ast.AST] = []
        for node in ast.walk(corpus.trees[serve_anchor]):
            if isinstance(node, ast.Call):
                roots.extend(node.args)
                roots.extend(kw.value for kw in node.keywords)
            elif isinstance(node, ast.Assign):
                roots.append(node.value)
        literals = [
            lit
            for root in roots
            for sub in ast.walk(root)
            if (lit := str_const(sub)) is not None
        ]
        for site in sorted(s for s in known if s.startswith("serve.")):
            if not any(site in lit for lit in literals):
                yield Finding(
                    rule="lease-discipline",
                    path=serve_anchor,
                    line=1,
                    message=f"serving fault site {site!r} is never "
                    f"exercised by the serving suite",
                    hint="add a kill/takeover (or registry-pin) case "
                    "naming it in tests/test_serve.py",
                )

    # (c) lease-state mutations in serve/queue.py persist in-function
    queue_path = corpus.find("serve/queue.py")
    if queue_path is not None:
        for fn in ast.walk(corpus.trees[queue_path]):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            line = _lease_mutation_line(fn)
            if line is None:
                continue
            persists = any(
                isinstance(n, ast.Call)
                and (
                    "save" in call_name(n)
                    or call_name(n) in ("write_durable", "replace_durable")
                )
                for n in ast.walk(fn)
            )
            if not persists:
                yield Finding(
                    rule="lease-discipline",
                    path=queue_path,
                    line=line,
                    message=f"lease state mutated in {fn.name}() without a "
                    f"durable persist in the same function",
                    hint="call save() (the journal's durable write) in the "
                    "same transaction that moves lease/token state",
                )


def _lease_mutation_line(fn: ast.AST) -> int | None:
    """First line in ``fn`` that mutates lease state: an assignment
    whose target touches a ``"lease"``/``"token"`` subscript, or a
    ``.pop("lease")`` call."""
    for node in ast.walk(fn):
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                for sub in ast.walk(t):
                    if isinstance(sub, ast.Subscript) and str_const(
                        sub.slice
                    ) in ("lease", "token"):
                        return node.lineno
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "pop"
            and node.args
            and str_const(node.args[0]) == "lease"
        ):
            return node.lineno
    return None


# ------------------------------------------------ rule: deadline discipline

# the time-stamp key family in the serving journal: anything from it
# must carry the domain suffix — "_m" (a time.monotonic() reading) or
# "_s" (a duration). A bare "deadline"/"expires" key is exactly the
# place a wall-clock reading sneaks in and survives review, because it
# works until the first NTP step (or cross-restart comparison) voids
# every expiry at once.
_STAMP_KEY_PREFIXES = ("deadline", "expires", "progress", "admitted",
                       "claimed")


@register(
    "deadline-discipline",
    "serve/ deadlines/expiries live in the monotonic domain; every "
    "journal state literal is registered and serving-suite exercised",
)
def check_deadline_discipline(corpus: Corpus) -> Iterator[Finding]:
    """Three checks, all cheap to drift past review:

    (a) STAMP-KEY NAMING: in ``serve/``, any journal key from the
        time-stamp family (deadline/expires/progress/admitted/claimed)
        must end in ``_m`` (monotonic stamp) or ``_s`` (duration) —
        the naming convention IS the domain annotation the clock rule
        cannot see across a dict boundary;
    (b) MONOTONIC DERIVATION: a function that writes a ``*_m`` key
        must read ``time.monotonic()`` in the same scope — a ``*_m``
        key fed from anything else is a lie wearing the convention;
    (c) STATE REGISTRY: every literal a ``serve/`` file assigns into a
        journal entry's ``state`` is registered in
        ``serve/queue.py`` JOB_STATES, and every registered state is
        exercised by ``tests/test_serve.py`` as a literal — an
        unregistered terminal state (expired, quarantined, ...) would
        silently fall out of compaction/idle/status logic."""
    serve_paths = [
        p for p in corpus.trees
        if "serve" in p.split("/")[:-1]
    ]

    # (a) stamp-key naming
    for path in serve_paths:
        for lit, line in _dict_key_literals(corpus.trees[path]):
            if not lit.startswith(_STAMP_KEY_PREFIXES):
                continue
            if lit.endswith(("_m", "_s")):
                continue
            yield Finding(
                rule="deadline-discipline",
                path=path,
                line=line,
                message=f"time-stamp key {lit!r} without a clock-domain "
                f"suffix",
                hint="name monotonic stamps '<key>_m' and durations "
                "'<key>_s' — the suffix is the domain annotation the "
                "deadline arithmetic is checked against",
            )

    # (b) *_m keys must be derived from time.monotonic() in-function
    for path in serve_paths:
        for fn in ast.walk(corpus.trees[path]):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            line = _monotonic_stamp_assign_line(fn)
            if line is None:
                continue
            # a lease-store clock read (``store.now()`` /
            # ``store.capture_epoch()``) counts as a monotonic
            # derivation: the store IS the stamp clock (local = machine
            # monotonic; sharedfs = the calibrated fs clock), and
            # forcing raw time.monotonic() back into those functions
            # would undo exactly the domain seam host-locality guards
            mentions = any(
                (isinstance(n, ast.Attribute) and n.attr == "monotonic")
                or (isinstance(n, ast.Name) and "monotonic" in n.id)
                or (
                    isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr in ("now", "capture_epoch")
                    and "store" in (expr_path(n.func.value) or "")
                )
                for n in ast.walk(fn)
            )
            if not mentions:
                yield Finding(
                    rule="deadline-discipline",
                    path=path,
                    line=line,
                    message=f"monotonic-domain key written in {fn.name}() "
                    f"without a time.monotonic() reading in scope",
                    hint="compute *_m stamps from time.monotonic() in the "
                    "same function (wall clocks void every expiry on an "
                    "NTP step)",
                )

    # (c) state-literal registry + serving-suite exercise. The
    # registry anchor is serve/states.py (the declared state machine);
    # pre-refactor corpora (the fixture corpora in tests/test_lint.py)
    # that still keep JOB_STATES in serve/queue.py anchor there.
    states_anchor = corpus.find("serve/states.py")
    if states_anchor is None:
        states_anchor = corpus.find("serve/queue.py")
    if states_anchor is None:
        return
    states, states_line = str_tuple_assign(
        corpus.trees[states_anchor], "JOB_STATES"
    )
    if not states:
        yield Finding(
            rule="deadline-discipline",
            path=states_anchor,
            line=1,
            message="JOB_STATES literal tuple not found",
            hint="keep JOB_STATES a module-level tuple of string literals "
            "so the state machine stays statically checkable",
        )
        return
    state_set = set(states)
    for path in serve_paths:
        for lit, line in _journal_state_literals(corpus.trees[path]):
            if lit not in state_set:
                yield Finding(
                    rule="deadline-discipline",
                    path=path,
                    line=line,
                    message=f"journal state literal {lit!r} is not "
                    f"registered in the JOB_STATES registry "
                    f"(serve/states.py)",
                    hint="register the state (and cover it in "
                    "tests/test_serve.py) or fix the typo",
                )
    serve_anchor = corpus.find("tests/test_serve.py")
    if serve_anchor is None:
        return
    roots: list[ast.AST] = []
    for node in ast.walk(corpus.trees[serve_anchor]):
        if isinstance(node, ast.Call):
            roots.extend(node.args)
            roots.extend(kw.value for kw in node.keywords)
        elif isinstance(node, ast.Assign):
            roots.append(node.value)
        elif isinstance(node, ast.Compare):
            # `assert status["state"] == "expired"` is the natural way
            # a test exercises a state — comparisons count, docstrings
            # still don't (bare Expr statements are never roots)
            roots.extend(node.comparators)
    literals = [
        lit
        for root in roots
        for sub in ast.walk(root)
        if (lit := str_const(sub)) is not None
    ]
    for state in states:
        if not any(state in lit for lit in literals):
            yield Finding(
                rule="deadline-discipline",
                path=serve_anchor,
                line=1,
                message=f"journal state {state!r} is never exercised by "
                f"the serving suite",
                hint="add a test driving a job through it (or a "
                "registry-pin naming it) in tests/test_serve.py",
            )


def _dict_key_literals(tree: ast.Module) -> Iterator[tuple[str, int]]:
    """String literals in DICT-KEY position: dict-literal keys,
    subscript slices, and the key arg of .get/.pop/.setdefault — the
    places a journal field name can appear."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if k is None:
                    continue
                s = str_const(k)
                if s is not None:
                    yield s, k.lineno
        elif isinstance(node, ast.Subscript):
            s = str_const(node.slice)
            if s is not None:
                yield s, node.lineno
        elif (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("get", "pop", "setdefault")
            and node.args
        ):
            s = str_const(node.args[0])
            if s is not None:
                yield s, node.lineno


def _monotonic_stamp_assign_line(fn: ast.AST) -> int | None:
    """First line in ``fn`` assigning a subscript whose literal key
    ends in ``_m`` (a monotonic stamp write)."""
    for node in ast.walk(fn):
        if not isinstance(node, (ast.Assign, ast.AugAssign)):
            continue
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            for sub in ast.walk(t):
                if isinstance(sub, ast.Subscript):
                    s = str_const(sub.slice)
                    if s is not None and s.endswith("_m"):
                        return node.lineno
    return None


def _journal_state_literals(tree: ast.Module) -> Iterator[tuple[str, int]]:
    """State literals written INTO journal entries: ``<x>["state"] =
    "lit"`` subscript assignments, and the ``"state"`` value of any
    dict literal ASSIGNED to a name or into a container (which covers
    both ``self.jobs[id] = {"state": ...}`` and the temporary-dict
    pattern ``entry = {"state": ...}; self.jobs[id] = entry``).
    Read-side literals built inline in ``return`` expressions (status
    rendering, client pseudo-states like "submitted") are not journal
    writes and are deliberately out of scope."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if (
                isinstance(t, ast.Subscript)
                and str_const(t.slice) == "state"
            ):
                s = str_const(node.value)
                if s is not None:
                    yield s, node.lineno
        if isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                if k is not None and str_const(k) == "state":
                    s = str_const(v)
                    if s is not None:
                        yield s, node.lineno


# --------------------------------------------------------- rule: hook guard

@register(
    "hook-guard",
    "recorder span/event/xfer hooks on hot paths must be behind a "
    "single None check",
)
def check_hook_guard(corpus: Corpus) -> Iterator[Finding]:
    """The zero-cost-when-off contract (same discipline as
    ``faults.fault_point``): with tracing off, every telemetry hook in
    the per-chunk path must cost one None check — so a direct
    ``tr.span(...)`` / ``tr.event(...)`` / ``tr.xfer(...)`` on a local
    recorder variable must sit inside ``if tr is not None:`` (or the
    ``else`` of ``if tr is None:``) — dotted receivers (``ctx.tr.span``,
    ``self._recorder.event``) included, guarded on the same dotted
    path. Module-hook helpers (``emit_event``, ``fault_point``) carry
    the check internally and are exempt; a bare ``self.span(...)`` is
    the recorder's own internals and is exempt too."""
    from duplexumiconsensusreads_tpu.analysis.engine import expr_path

    for path in corpus.package_paths():
        tree = corpus.trees.get(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            if not (
                isinstance(fn, ast.Attribute)
                and fn.attr in ("span", "event", "xfer")
            ):
                continue
            var = expr_path(fn.value)
            if var is None or var == "self":
                continue  # no stable identity / recorder internals
            if not guarded_not_none(node, var):
                yield Finding(
                    rule="hook-guard",
                    path=path,
                    line=node.lineno,
                    message=f"unguarded telemetry hook {var}.{fn.attr}(...)",
                    hint=f"wrap in `if {var} is not None:` — hooks must be "
                    "a single None check when tracing is off",
                )


# ------------------------------------------------------ rule: state machine

# calls that prove (by raising JobFenced otherwise) that the entry is
# in a CLAIMED state — fence checks are from-state evidence exactly
# like an explicit state comparison
_FENCE_GUARD_CALLS = ("_check_fence", "check_fence")


def _state_views(states: list, transitions: dict) -> dict:
    """The derived state families, recomputed from the declared graph
    with the same formulas serve/states.py uses (tests/test_serve.py
    pins both against the same literals, so they cannot drift apart
    silently). Keyed by the registry NAMES so membership tests like
    ``entry.get("state") in CLAIMED_STATES`` resolve to member sets.
    JOB_STATES is deliberately NOT an evidence family: membership in
    the full state set proves nothing about the from-state, and
    counting it would let `if state in JOB_STATES` launder any write
    past the terminal/undeclared checks."""
    return {
        "TERMINAL_STATES": {s for s in states if not transitions.get(s)},
        "CLAIMED_STATES": {
            s for s in states if "quarantined" in transitions.get(s, ())
        },
        "OPEN_STATES": {s for s in states if transitions.get(s)},
    }


def _from_state_evidence(fn: ast.AST, state_set: set, views: dict) -> set:
    """The set of from-states the enclosing function proves it is
    handling: literal state comparisons (``== / != / in / not in``,
    asserts included — Compare nodes all), membership tests against a
    named state family, and fence-guard calls (which prove CLAIMED)."""
    ev: set = set()
    for n in ast.walk(fn):
        if isinstance(n, ast.Call) and call_name(n) in _FENCE_GUARD_CALLS:
            ev |= views["CLAIMED_STATES"]
        if not isinstance(n, ast.Compare):
            continue
        for e in (n.left, *n.comparators):
            s = str_const(e)
            if s is not None and s in state_set:
                ev.add(s)
            name = (
                e.id if isinstance(e, ast.Name)
                else e.attr if isinstance(e, ast.Attribute)
                else None
            )
            if name in views:
                ev |= views[name]
            if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
                for el in e.elts:
                    s = str_const(el)
                    if s is not None and s in state_set:
                        ev.add(s)
    return ev


def _is_jobs_expr(e: ast.AST) -> bool:
    return (isinstance(e, ast.Name) and e.id == "jobs") or (
        isinstance(e, ast.Attribute) and e.attr == "jobs"
    )


def _dict_reaches_jobs(node: ast.Assign, tree: ast.Module) -> bool:
    """Does this dict-literal assignment land in the jobs cache —
    directly (``jobs[x] = {...}``) or via the temporary-dict pattern
    (``entry = {...}; ... jobs[x] = entry`` in the same scope)? A
    status/response dict that never reaches the cache is read-side
    rendering, not a journal-entry creation."""
    def _into_jobs(t: ast.AST) -> bool:
        return isinstance(t, ast.Subscript) and _is_jobs_expr(t.value)

    if any(_into_jobs(t) for t in node.targets):
        return True
    names = {t.id for t in node.targets if isinstance(t, ast.Name)}
    if not names:
        return False
    scope = enclosing_function(node) or tree
    return any(
        isinstance(n, ast.Assign)
        and isinstance(n.value, ast.Name)
        and n.value.id in names
        and any(_into_jobs(t) for t in n.targets)
        for n in ast.walk(scope)
    )


def _state_write_sites(tree: ast.Module, state_set: set):
    """Yield (kind, to_states, node): "create" for a dict literal with
    a literal ``state`` key that reaches the jobs cache (direct
    subscript or accept_one's temporary-dict pattern — see
    :func:`_dict_reaches_jobs`), "transition" for a ``<x>["state"] =
    ...`` subscript write. to_states collects every registered literal
    in the written value (an IfExp write like claim's contributes all
    of its branches); writes with no registered literal are variable
    relays — unverifiable here, and the registration rule already
    polices unregistered literals."""
    for node in ast.walk(tree):
        # method-call writes: entry.update({"state": ...}) /
        # entry.update(state=...) / entry.setdefault("state", ...) —
        # the same journal move in call clothing; without these the
        # gate would be fail-open for exactly the writes a subscript
        # grep can't see
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ("update", "setdefault")
        ):
            tos: set = set()
            if node.func.attr == "setdefault":
                if (
                    len(node.args) >= 2
                    and str_const(node.args[0]) == "state"
                    and (s := str_const(node.args[1])) is not None
                    and s in state_set
                ):
                    tos.add(s)
            else:
                for a in node.args:
                    if isinstance(a, ast.Dict):
                        for k, v in zip(a.keys, a.values):
                            if k is not None and str_const(k) == "state":
                                s = str_const(v)
                                if s is not None and s in state_set:
                                    tos.add(s)
                for kw in node.keywords:
                    if kw.arg == "state":
                        s = str_const(kw.value)
                        if s is not None and s in state_set:
                            tos.add(s)
            if tos:
                yield "transition", tos, node
            continue
        if not isinstance(node, ast.Assign):
            continue
        for t in node.targets:
            if isinstance(t, ast.Subscript) and str_const(t.slice) == "state":
                tos = {
                    s for sub in ast.walk(node.value)
                    if (s := str_const(sub)) is not None and s in state_set
                }
                if tos:
                    yield "transition", tos, node
        if isinstance(node.value, ast.Dict) and _dict_reaches_jobs(
            node, tree
        ):
            for k, v in zip(node.value.keys, node.value.values):
                if k is not None and str_const(k) == "state":
                    s = str_const(v)
                    if s is not None and s in state_set:
                        yield "create", {s}, node


@register(
    "state-machine",
    "serve/ journal states move only along serve/states.py TRANSITIONS: "
    "no undeclared edges, no terminal writes, no unreachable or dead "
    "declared states",
)
def check_state_machine(corpus: Corpus) -> Iterator[Finding]:
    """The protocol model-check. serve/states.py declares the graph
    (JOB_STATES / INITIAL_STATES / TRANSITIONS); this rule rebuilds the
    graph the CODE implements — every state write in ``serve/``,
    sourced with its from-state evidence (state comparisons, asserts,
    fence-guard calls in the same function) — and diffs the two:

    (a) registry self-consistency: every state has a TRANSITIONS row,
        every edge endpoint is registered, initial states registered;
    (b) reachability: every state is reachable from the initial states
        (an unreachable state is dead protocol the sweeps/compaction
        still pay for);
    (c) every observed write is a declared edge: a creation writes an
        INITIAL state, a transition write's target is a declared
        successor of at least one evidenced from-state — and a write
        whose only evidence is terminal states is resurrecting a
        finished job;
    (d) a transition write with NO from-state evidence is itself a
        finding: un-evidenced writes are how undeclared edges ship;
    (e) dead declared edges: a declared transition no write site
        implements is protocol fiction — prune it or implement it;
    (f) the serving suite exercises the declared graph: a registry-pin
        or parametrize referencing TRANSITIONS, or per-edge
        ``"src->dst"`` literals."""
    states_path = corpus.find("serve/states.py")
    if states_path is None:
        return
    tree = corpus.trees[states_path]
    states, states_line = str_tuple_assign(tree, "JOB_STATES")
    transitions, t_line = str_dict_assign(tree, "TRANSITIONS")
    initial, _ = str_tuple_assign(tree, "INITIAL_STATES")
    if not states or not transitions:
        yield Finding(
            rule="state-machine",
            path=states_path,
            line=1,
            message="JOB_STATES / TRANSITIONS literals not found",
            hint="keep JOB_STATES a literal string tuple and TRANSITIONS "
            "a literal {state: (successor, ...)} dict so the model "
            "checker can read the declared graph",
        )
        return
    state_set = set(states)

    # (a) self-consistency
    for s in states:
        if s not in transitions:
            yield Finding(
                rule="state-machine",
                path=states_path,
                line=t_line,
                message=f"state {s!r} has no TRANSITIONS row",
                hint="every registered state needs a row — () for "
                "terminal states",
            )
    for src, succs in transitions.items():
        if src not in state_set:
            yield Finding(
                rule="state-machine",
                path=states_path,
                line=t_line,
                message=f"TRANSITIONS key {src!r} is not in JOB_STATES",
                hint="register the state or drop the row",
            )
        for dst in succs:
            if dst not in state_set:
                yield Finding(
                    rule="state-machine",
                    path=states_path,
                    line=t_line,
                    message=f"TRANSITIONS edge {src!r} -> {dst!r} targets "
                    f"an unregistered state",
                    hint="register the state or fix the typo",
                )
    roots = [s for s in initial if s in state_set] or (
        ["queued"] if "queued" in state_set else []
    )
    for s in initial:
        if s not in state_set:
            yield Finding(
                rule="state-machine",
                path=states_path,
                line=states_line,
                message=f"INITIAL_STATES entry {s!r} is not in JOB_STATES",
                hint="register the state or fix the typo",
            )

    # (b) reachability from admission
    seen = set(roots)
    frontier = list(roots)
    while frontier:
        for dst in transitions.get(frontier.pop(), ()):
            if dst in state_set and dst not in seen:
                seen.add(dst)
                frontier.append(dst)
    for s in states:
        if s not in seen:
            yield Finding(
                rule="state-machine",
                path=states_path,
                line=states_line,
                message=f"state {s!r} is unreachable from the initial "
                f"states (no admission path reaches it)",
                hint="declare a transition chain from an INITIAL_STATES "
                "entry, or drop the dead state",
            )

    # (c)/(d) observed writes vs the declared graph
    views = _state_views(states, transitions)
    initial_set = set(initial) or {"queued"}
    observed: set = set()
    serve_paths = [
        p for p in corpus.package_paths()
        if "serve" in p.split("/")[:-1] and p != states_path
    ]
    for path in serve_paths:
        for kind, tos, node in _state_write_sites(
            corpus.trees[path], state_set
        ):
            if kind == "create":
                for t in sorted(tos):
                    if t not in initial_set:
                        yield Finding(
                            rule="state-machine",
                            path=path,
                            line=node.lineno,
                            message=f"journal entry created in non-initial "
                            f"state {t!r}",
                            hint="entries are created in INITIAL_STATES "
                            "(admission); every other state must be "
                            "reached via a declared transition",
                        )
                continue
            fn = enclosing_function(node)
            ev = (
                _from_state_evidence(fn, state_set, views)
                if fn is not None else set()
            )
            if not ev:
                name = getattr(fn, "name", "<module>")
                yield Finding(
                    rule="state-machine",
                    path=path,
                    line=node.lineno,
                    message=f"state transition written in {name}() with no "
                    f"from-state evidence in scope",
                    hint="guard (or assert) the entry's current state — "
                    "or fence it — in the same function, so the "
                    "transition's source is checkable",
                )
                continue
            for t in sorted(tos):
                legal_from = {
                    f for f in ev if t in transitions.get(f, ())
                }
                if legal_from:
                    observed |= {(f, t) for f in legal_from}
                    continue
                if ev <= views["TERMINAL_STATES"]:
                    yield Finding(
                        rule="state-machine",
                        path=path,
                        line=node.lineno,
                        message=f"write of {t!r} over a terminal-state "
                        f"entry (evidence: {sorted(ev)})",
                        hint="terminal states have no successors — a "
                        "finished job's journal entry may never be "
                        "rewritten (its results/ file is the record)",
                    )
                else:
                    yield Finding(
                        rule="state-machine",
                        path=path,
                        line=node.lineno,
                        message=f"undeclared transition "
                        f"{sorted(ev)} -> {t!r}",
                        hint="declare the edge in serve/states.py "
                        "TRANSITIONS (and cover it) or fix the write",
                    )

    # (e) declared edges no code implements
    for src in states:
        for dst in transitions.get(src, ()):
            if dst in state_set and (src, dst) not in observed:
                yield Finding(
                    rule="state-machine",
                    path=states_path,
                    line=t_line,
                    message=f"declared transition {src!r} -> {dst!r} has "
                    f"no write site in serve/",
                    hint="implement the edge (a guarded state write) or "
                    "prune the declaration — a fictional edge hides "
                    "real drift",
                )

    # (f) the serving suite exercises the declared graph
    anchor = corpus.find("tests/test_serve.py")
    if anchor is None:
        return
    anchor_tree = corpus.trees[anchor]
    blanket = any(
        (isinstance(n, ast.Name) and n.id == "TRANSITIONS")
        or (isinstance(n, ast.Attribute) and n.attr == "TRANSITIONS")
        for n in ast.walk(anchor_tree)
    )
    if blanket:
        return  # a registry-pin/parametrize over the table covers it
    roots_: list[ast.AST] = []
    for n in ast.walk(anchor_tree):
        if isinstance(n, ast.Call):
            roots_.extend(n.args)
            roots_.extend(kw.value for kw in n.keywords)
        elif isinstance(n, ast.Assign):
            roots_.append(n.value)
        elif isinstance(n, (ast.For, ast.AsyncFor)):
            # `for edge in ("a->b", ...):` — the natural shape of a
            # per-edge driving loop
            roots_.append(n.iter)
        elif isinstance(n, ast.Compare):
            roots_.extend(n.comparators)
    literals = [
        lit
        for root in roots_
        for sub in ast.walk(root)
        if (lit := str_const(sub)) is not None
    ]
    for src in states:
        for dst in transitions.get(src, ()):
            edge = f"{src}->{dst}"
            if not any(edge in lit for lit in literals):
                yield Finding(
                    rule="state-machine",
                    path=anchor,
                    line=1,
                    message=f"declared transition {edge} is never "
                    f"exercised by the serving suite",
                    hint="add a test driving it (or a registry pin "
                    "walking serve.states.TRANSITIONS) in "
                    "tests/test_serve.py",
                )


# ------------------------------------------------------ rule: txn discipline

# calls that hold the device, the disk, or the clock hostage: none may
# run while journal.lock is held — every other daemon's every journal
# move convoys behind it
_TXN_SLOW_CALLS = {
    "fsync", "fsync_file", "sleep", "result",
    "stream_call_consensus", "run_slice", "splice_shards", "plan_shards",
}


def _is_journal_receiver(e: ast.AST) -> bool:
    """Does this ``.save()`` receiver look like the journal queue —
    ``self`` (inside SpoolQueue) or a ``*queue*``-named handle (the
    service's ``self.queue``)? Anything else (a figure, a config
    object, a report writer) has its own save semantics and is not a
    journal persist."""
    from duplexumiconsensusreads_tpu.analysis.engine import expr_path

    path = expr_path(e)
    if path is None:
        return False
    last = path.split(".")[-1]
    return last == "self" or "queue" in last.lower()


def _inside_txn(node: ast.AST) -> bool:
    """Is ``node`` lexically inside a ``with <x>._txn():`` body?"""
    for a in ancestors(node):
        if isinstance(a, (ast.With, ast.AsyncWith)) and any(
            isinstance(item.context_expr, ast.Call)
            and call_name(item.context_expr) == "_txn"
            for item in a.items
        ):
            return True
    return False


def _jobs_mutation(node: ast.AST) -> str | None:
    """Describe a mutation of the ``jobs`` journal cache, or None:
    subscript/attribute (re)assignment, ``del jobs[...]``, or a
    mutating method call on a ``jobs`` receiver (the receiver test is
    :func:`_is_jobs_expr`, shared with the state-machine rule so the
    two passes can never disagree about what the cache is)."""
    if isinstance(node, (ast.Assign, ast.AugAssign)):
        targets = (
            node.targets if isinstance(node, ast.Assign) else [node.target]
        )
        for t in targets:
            if isinstance(t, ast.Subscript) and _is_jobs_expr(t.value):
                return "jobs[...] assignment"
            if _is_jobs_expr(t):
                return "jobs cache rebind"
    if isinstance(node, ast.Delete):
        for t in node.targets:
            if isinstance(t, ast.Subscript) and _is_jobs_expr(t.value):
                return "del jobs[...]"
    if (
        isinstance(node, ast.Call)
        and isinstance(node.func, ast.Attribute)
        and node.func.attr in _MUTATORS
        and _is_jobs_expr(node.func.value)
    ):
        return f"jobs.{node.func.attr}(...)"
    return None


@register(
    "txn-discipline",
    "serve/ journal/jobs-cache mutations happen inside a _txn; no slow "
    "ops or nested txn acquisition inside a txn body",
)
def check_txn_discipline(corpus: Corpus) -> Iterator[Finding]:
    """The flock'd-transaction contract (serve/queue.py \"Fleet
    transactions\"): every journal mutation is reload -> mutate ->
    durable persist under journal.lock. Three drift classes:

    (a) a ``jobs``-cache mutation or ``save()`` persist outside any
        ``with self._txn():`` body — unless the enclosing function is
        declared caller-holds-the-lock (``*_locked`` suffix, the
        ``TXN_CACHE_HELPERS`` registry in serve/queue.py, or
        ``__init__``): an untransacted mutation is the refresh()
        lost-renewal bug class, a silent fleet write race;
    (b) a slow call (fsync/sleep/compress/a future's result()/device
        dispatch) lexically inside a txn body: journal.lock serializes
        the WHOLE fleet's journal moves, so holding it across slow work
        convoys every daemon (the deliberate exception — the durable
        result write sharing mark_done's fence transaction — routes
        through write_durable, which is not in the slow-call set);
    (c) nested txn acquisition: a txn body opening another txn (a
        second ``_txn()`` with, or a call to any method that opens one)
        self-deadlocks the daemon under flock."""
    serve_paths = [
        p for p in corpus.package_paths() if "serve" in p.split("/")[:-1]
    ]
    if not serve_paths:
        return
    # methods that OPEN a transaction, collected across serve/: any
    # call to one of these inside a txn body is a nested acquisition
    txn_methods: set = set()
    for path in serve_paths:
        for fn in ast.walk(corpus.trees[path]):
            if isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)) and any(
                isinstance(n, (ast.With, ast.AsyncWith))
                and any(
                    isinstance(i.context_expr, ast.Call)
                    and call_name(i.context_expr) == "_txn"
                    for i in n.items
                )
                for n in ast.walk(fn)
            ):
                txn_methods.add(fn.name)
    # caller-holds-the-lock helpers declared in the queue module
    helpers: set = {"__init__"}
    queue_path = corpus.find("serve/queue.py")
    if queue_path is not None:
        declared, _ = str_tuple_assign(
            corpus.trees[queue_path], "TXN_CACHE_HELPERS"
        )
        helpers |= set(declared)

    for path in serve_paths:
        tree = corpus.trees[path]
        for node in ast.walk(tree):
            # (c) direct nesting: a txn `with` whose ancestors already
            # hold one (the call-a-txn-method form is handled below)
            if isinstance(node, (ast.With, ast.AsyncWith)) and any(
                isinstance(i.context_expr, ast.Call)
                and call_name(i.context_expr) == "_txn"
                for i in node.items
            ) and _inside_txn(node):
                yield Finding(
                    rule="txn-discipline",
                    path=path,
                    line=node.lineno,
                    message="nested journal transaction: `with _txn()` "
                    "inside a txn body",
                    hint="flock self-deadlocks on re-acquisition from a "
                    "second fd — one transaction owns the whole move",
                )
            # (a) mutations + persists must be transacted
            desc = _jobs_mutation(node)
            is_save = (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "save"
                and _is_journal_receiver(node.func.value)
            )
            if (desc or is_save) and not _inside_txn(node):
                fn = enclosing_function(node)
                name = getattr(fn, "name", None)
                exempt = name is not None and (
                    name.endswith("_locked") or name in helpers
                )
                if not exempt:
                    what = desc or "journal save()"
                    yield Finding(
                        rule="txn-discipline",
                        path=path,
                        line=node.lineno,
                        message=f"{what} outside a journal transaction "
                        f"in {name or '<module>'}()",
                        hint="wrap the mutation in `with self._txn():` "
                        "(or mark the helper caller-holds-the-lock: "
                        "*_locked suffix / TXN_CACHE_HELPERS)",
                    )
            if not isinstance(node, ast.Call) or not _inside_txn(node):
                continue
            name = call_name(node)
            # (b) slow ops under journal.lock
            if name in _TXN_SLOW_CALLS or "compress" in name.lower():
                yield Finding(
                    rule="txn-discipline",
                    path=path,
                    line=node.lineno,
                    message=f"slow call {name}() inside a journal "
                    f"transaction body",
                    hint="do the slow work outside the txn — "
                    "journal.lock serializes the whole fleet's "
                    "journal moves",
                )
            # (c) nested acquisition: a call to any txn-opening method
            # inside a txn body (the `self._txn()` call that opens THIS
            # body never matches — "_txn" itself opens no inner txn)
            if name in txn_methods:
                yield Finding(
                    rule="txn-discipline",
                    path=path,
                    line=node.lineno,
                    message=f"nested journal transaction: {name}() "
                    f"opens a txn inside a txn body",
                    hint="flock self-deadlocks on re-acquisition "
                    "from a second fd — restructure so one "
                    "transaction owns the whole move",
                )


# ------------------------------------------------------ rule: fence dominance

# the durable job-path commits: every one must carry the caller's lease
# identity (daemon_id + fencing token — the journal transaction fences
# on them) or run under the shared fenced-renewal guard
_PUBLISH_CALLS = {
    "mark_done", "mark_failed", "mark_expired", "requeue",
    "register_shards",
}
# the registered fence helpers: a call to any of these in the same
# function dominates the publish (worker.fenced_renew is THE shared
# guard; the queue-internal _check_fence is the transaction-side check)
_FENCE_CALLS = {"fenced_renew", "_fenced_renew", "verify_lease",
                "_check_fence"}


@register(
    "fence-dominance",
    "serve/ durable publishes (mark_*/requeue/register_shards) must be "
    "fenced: lease identity passed, or a fenced-renew guard in scope",
)
def check_fence_dominance(corpus: Corpus) -> Iterator[Finding]:
    """The zombie-writer gate: a daemon that lost its lease must not be
    able to publish, requeue or journal ANYTHING for the job (the
    reclaiming daemon owns it now). The queue's mutating methods fence
    inside their transaction — but only when the caller passes its
    lease identity, so an identity-less call site is an unfenced escape
    hatch that ships silently and loses a race years later. Every call
    to a publish-family method outside serve/queue.py must therefore
    (a) mention the lease identity (a ``token``/``daemon_id`` name in
    its arguments), or (b) sit in a function that runs a registered
    fence guard (``fenced_renew``/``verify_lease``) itself."""
    for path in corpus.package_paths():
        if "serve" not in path.split("/")[:-1] or path.endswith(
            "serve/queue.py"
        ):
            continue
        tree = corpus.trees[path]
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if call_name(node) not in _PUBLISH_CALLS:
                continue
            args: list[ast.AST] = list(node.args) + [
                kw.value for kw in node.keywords
            ]
            kw_names = [kw.arg for kw in node.keywords if kw.arg]
            fenced = any(
                "token" in n or "daemon" in n
                for n in kw_names
            ) or any(
                ("token" in sub.id.lower() or "daemon" in sub.id.lower())
                if isinstance(sub, ast.Name)
                else ("token" in sub.attr.lower()
                      or "daemon" in sub.attr.lower())
                if isinstance(sub, ast.Attribute)
                else False
                for a in args
                for sub in ast.walk(a)
            )
            if not fenced:
                fn = enclosing_function(node)
                scope = fn if fn is not None else tree
                fenced = any(
                    isinstance(n, ast.Call)
                    and call_name(n) in _FENCE_CALLS
                    for n in ast.walk(scope)
                )
            if not fenced:
                yield Finding(
                    rule="fence-dominance",
                    path=path,
                    line=node.lineno,
                    message=f"unfenced durable publish "
                    f"{call_name(node)}(...)",
                    hint="pass the slice's lease identity (daemon_id + "
                    "token — the journal txn fences on them) or guard "
                    "the function with fenced_renew",
                )


# -------------------------------------------------- rule: exception contract

# the exceptions whose HANDLING is part of the protocol, not local
# style. "base": the exact declared base class — JobFenced/InjectedKill
# are BaseException precisely so no `except Exception` ladder can
# absorb a modelled kill or a fence abort; changing the base voids the
# kill-equals-SIGKILL and zombie-fencing contracts everywhere at once.
# "reraise": deterministic invariant violations — a retry re-derives
# the identical failure, so any handler naming them must re-raise
# immediately, and no broad handler may sit between a raising call and
# its re-raise guard.
CONTRACT_EXCEPTIONS = {
    "JobFenced": {"base": "BaseException", "reraise": False},
    "InjectedKill": {"base": "BaseException", "reraise": False},
    "D2hCompactionOverflow": {"base": "RuntimeError", "reraise": True},
}


def _handler_type_names(type_node: ast.AST | None) -> set:
    names: set = set()
    if type_node is None:
        return names
    nodes = (
        list(type_node.elts) if isinstance(type_node, ast.Tuple)
        else [type_node]
    )
    for n in nodes:
        if isinstance(n, ast.Name):
            names.add(n.id)
        elif isinstance(n, ast.Attribute):
            names.add(n.attr)
    return names


def _raised_name(exc: ast.AST | None) -> str | None:
    if exc is None:
        return None
    if isinstance(exc, ast.Call):
        return call_name(exc)
    if isinstance(exc, ast.Name):
        return exc.id
    if isinstance(exc, ast.Attribute):
        return exc.attr
    return None


@register(
    "exception-contract",
    "runtime//serve/ handlers honour the contract exceptions: "
    "BaseException kills stay unabsorbed, deterministic overflows "
    "re-raise immediately",
)
def check_exception_contract(corpus: Corpus) -> Iterator[Finding]:
    """Walks every handler in ``runtime/`` + ``serve/`` against
    :data:`CONTRACT_EXCEPTIONS`:

    (a) each contract exception's class keeps its declared base — a
        JobFenced quietly rebased onto Exception would be absorbed by
        every job-scoped ``except Exception`` and break zombie fencing
        with no test noticing until a takeover race lands;
    (b) no bare ``except:`` — it absorbs the BaseException contracts
        (InjectedKill's kill-equals-SIGKILL model, JobFenced aborts);
    (c) an ``except BaseException`` handler must re-raise or capture
        its exception (store-and-reraise, the service's fatal-path
        idiom) — silently swallowing one un-models a kill;
    (d) a handler naming a re-raise-immediately exception must have
        ``raise`` as its FIRST statement: log-then-retry on a
        deterministic overflow burns the whole retry/isolation ladder
        re-deriving one invariant violation;
    (e) a ``try`` whose body calls a function that (transitively, one
        wrapper hop) raises a re-raise-immediately exception must not
        absorb it in a broad Exception/BaseException handler without a
        dedicated re-raise handler first — the retry-ladder shape that
        motivated the contract."""
    scoped = [
        p for p in corpus.package_paths()
        if {"runtime", "serve"} & set(p.split("/")[:-1])
    ]
    reraise_names = {
        name for name, spec in CONTRACT_EXCEPTIONS.items() if spec["reraise"]
    }

    # (a) declared bases
    for path in scoped:
        for node in ast.walk(corpus.trees[path]):
            if not isinstance(node, ast.ClassDef):
                continue
            spec = CONTRACT_EXCEPTIONS.get(node.name)
            if spec is None:
                continue
            bases = {
                b.id if isinstance(b, ast.Name)
                else b.attr if isinstance(b, ast.Attribute) else "?"
                for b in node.bases
            }
            if spec["base"] not in bases:
                yield Finding(
                    rule="exception-contract",
                    path=path,
                    line=node.lineno,
                    message=f"{node.name} must derive {spec['base']} "
                    f"(declared contract), found {sorted(bases)}",
                    hint="the exception's BASE is the contract: "
                    "BaseException contracts must sail through every "
                    "`except Exception` ladder",
                )

    # direct raisers of re-raise-immediately exceptions, plus one
    # wrapper hop (the unpack()-style local adapters the retry ladders
    # actually call); deeper call chains end at a job boundary where
    # failing the job IS the contract, so propagation stops here
    direct: set = set()
    for path in scoped:
        for fn in ast.walk(corpus.trees[path]):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if any(
                isinstance(n, ast.Raise)
                and _raised_name(n.exc) in reraise_names
                for n in ast.walk(fn)
            ):
                direct.add(fn.name)
    raisers = set(direct)
    for path in scoped:
        for fn in ast.walk(corpus.trees[path]):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if fn.name in direct:
                continue
            if any(
                isinstance(n, ast.Call) and call_name(n) in direct
                for n in ast.walk(fn)
            ):
                raisers.add(fn.name)

    for path in scoped:
        tree = corpus.trees[path]
        for node in ast.walk(tree):
            # (b)/(c)/(d) per handler
            if isinstance(node, ast.ExceptHandler):
                if node.type is None:
                    yield Finding(
                        rule="exception-contract",
                        path=path,
                        line=node.lineno,
                        message="bare `except:` absorbs the BaseException "
                        "contracts (InjectedKill, JobFenced)",
                        hint="catch the exception classes you mean; a "
                        "modelled kill must leave real-SIGKILL state",
                    )
                    continue
                names = _handler_type_names(node.type)
                if "BaseException" in names:
                    reraises = any(
                        isinstance(n, ast.Raise)
                        for stmt in node.body
                        for n in ast.walk(stmt)
                    )
                    captures = node.name is not None and any(
                        isinstance(n, ast.Name) and n.id == node.name
                        for stmt in node.body
                        for n in ast.walk(stmt)
                    )
                    if not (reraises or captures):
                        yield Finding(
                            rule="exception-contract",
                            path=path,
                            line=node.lineno,
                            message="except BaseException handler neither "
                            "re-raises nor captures the exception",
                            hint="re-raise (cleanup handlers) or store it "
                            "for the fatal path (the service's "
                            "_fatal idiom) — never swallow a kill",
                        )
                hit = names & reraise_names
                if hit:
                    first = node.body[0] if node.body else None
                    ok = isinstance(first, ast.Raise) and (
                        first.exc is None
                        or (
                            isinstance(first.exc, ast.Name)
                            and first.exc.id == node.name
                        )
                    )
                    if not ok:
                        yield Finding(
                            rule="exception-contract",
                            path=path,
                            line=node.lineno,
                            message=f"handler for {sorted(hit)} must "
                            f"re-raise immediately (first statement)",
                            hint="deterministic invariant violations "
                            "re-derive identically — retrying or "
                            "logging-then-continuing burns the ladder "
                            "for nothing",
                        )
                continue
            # (e) retry-ladder absorption
            if not isinstance(node, ast.Try):
                continue
            body_calls = {
                call_name(n)
                for stmt in node.body
                for n in ast.walk(stmt)
                if isinstance(n, ast.Call)
            }
            risky = body_calls & raisers
            if not risky:
                continue
            for h in node.handlers:
                names = _handler_type_names(h.type)
                if names & reraise_names:
                    break  # dedicated guard precedes the broad ladder
                broad = h.type is None or {
                    "Exception", "BaseException"
                } & names
                if broad and not any(
                    isinstance(n, ast.Raise)
                    for stmt in h.body
                    for n in ast.walk(stmt)
                ):
                    yield Finding(
                        rule="exception-contract",
                        path=path,
                        line=h.lineno,
                        message=f"broad handler may absorb "
                        f"{sorted(reraise_names)} raised by "
                        f"{sorted(risky)}()",
                        hint="add `except D2hCompactionOverflow: raise` "
                        "(the deterministic-failure guard) before the "
                        "broad retry handler",
                    )
                    break


# ---------------------------------------------- rule: host locality

# The primitives the LOCAL lease backend stands on — pid-liveness
# probes and raw machine-monotonic readings compared against journal
# stamps — are exactly the operations that silently lie on a
# shared-filesystem spool: a pid is only meaningful on the host that
# spawned it, and two hosts' time.monotonic() epochs are unrelated
# numbers. The store seam (serve/store.py) exists so those operations
# have ONE home; this rule keeps them from leaking back into the
# serving layer, where they would work perfectly in every single-host
# test and corrupt the first multi-host deployment.
_XHOST_SITES = ("serve.hb", "serve.store")


@register(
    "host-locality",
    "pid-liveness probes and raw monotonic-vs-journal-stamp arithmetic "
    "are confined to the lease-store backend; the cross-host I/O sites "
    "are registered",
)
def check_host_locality(corpus: Corpus) -> Iterator[Finding]:
    """Four checks, each a way single-host assumptions re-enter serve/:

    (a) PID LIVENESS: ``serve/`` code outside ``serve/store.py`` must
        not call ``os.kill`` or ``_pid_alive`` — liveness belongs to
        the store (``store.pid_alive``/``store.observe``), which is the
        only place that knows whether a pid means anything on this
        spool (``os.getpid()`` as an identity read stays legal);
    (b) PID COMPARISON: comparing a journal record's ``"pid"`` field
        is a liveness/ownership decision in disguise — on a sharedfs
        spool two hosts can share a pid number, so the comparison
        must go through the store's reclaim verdict;
    (c) CLOCK-DOMAIN MIXING: an expression combining a direct
        ``time.monotonic()`` reading with a ``*_m`` journal-key read
        compares the local machine clock against the spool's stamp
        domain — correct locally, garbage cross-host. Stamp
        arithmetic must use ``store.now()`` (rule 8(b) accepts it as
        the monotonic derivation);
    (d) SITE REGISTRY: when the store backend exists, its two durable
        I/O steps (``serve.hb`` heartbeat write, ``serve.store``
        liveness scan) must be in runtime/faults.py KNOWN_SITES —
        registration is what routes them into the chaos blanket that
        proves the takeover ladders survive injected faults."""
    scoped = [
        p for p in corpus.trees
        if "serve" in p.split("/")[:-1] and p.split("/")[-1] != "store.py"
    ]

    def _key_reads(node: ast.AST) -> Iterator[str]:
        # literal dict-key reads: x["k"] subscripts and x.get("k")
        for sub in ast.walk(node):
            if isinstance(sub, ast.Subscript):
                s = str_const(sub.slice)
                if s is not None:
                    yield s
            elif (
                isinstance(sub, ast.Call)
                and isinstance(sub.func, ast.Attribute)
                and sub.func.attr == "get"
                and sub.args
            ):
                s = str_const(sub.args[0])
                if s is not None:
                    yield s

    def _reads_monotonic(node: ast.AST) -> bool:
        return any(
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "monotonic"
            for sub in ast.walk(node)
        )

    for path in scoped:
        flagged: set[tuple[str, int]] = set()
        for node in ast.walk(corpus.trees[path]):
            # (a) pid-liveness probes
            if isinstance(node, ast.Call):
                callee = expr_path(node.func)
                if callee == "os.kill" or call_name(node) == "_pid_alive":
                    yield Finding(
                        rule="host-locality",
                        path=path,
                        line=node.lineno,
                        message=f"pid-liveness probe "
                        f"({callee or call_name(node)}) outside the "
                        f"lease-store backend",
                        hint="route liveness through the store seam "
                        "(store.pid_alive / store.observe / "
                        "store.reclaim_reason) — a pid only means "
                        "anything on the host that spawned it",
                    )
                continue
            if not isinstance(node, (ast.Compare, ast.BinOp)):
                continue
            keys = set(_key_reads(node))
            # (b) ownership decisions off a journal "pid" field
            if (
                isinstance(node, ast.Compare)
                and "pid" in keys
                and ("pid", node.lineno) not in flagged
            ):
                flagged.add(("pid", node.lineno))
                yield Finding(
                    rule="host-locality",
                    path=path,
                    line=node.lineno,
                    message="comparison against a journal 'pid' field "
                    "outside the lease-store backend",
                    hint="pid ownership checks are liveness decisions — "
                    "they belong to store.reclaim_reason, where the "
                    "backend knows whether pids are comparable on "
                    "this spool",
                )
            # (c) machine clock vs stamp-domain arithmetic
            if (
                _reads_monotonic(node)
                and any(k.endswith("_m") for k in keys)
                and ("mono", node.lineno) not in flagged
            ):
                flagged.add(("mono", node.lineno))
                yield Finding(
                    rule="host-locality",
                    path=path,
                    line=node.lineno,
                    message="time.monotonic() compared/combined with a "
                    "*_m journal stamp",
                    hint="journal stamps live in the spool store's clock "
                    "domain — use store.now() for the other operand "
                    "(on a sharedfs spool the machine clock is an "
                    "unrelated epoch)",
                )
    # (d) cross-host I/O sites registered (only once the backend exists:
    # the pre-fleet fixture corpora in tests/test_lint.py have no
    # serve/store.py and owe no sites)
    if corpus.find("serve/store.py") is None:
        return
    faults_anchor = corpus.find("runtime/faults.py")
    if faults_anchor is None:
        return
    sites, sites_line = str_tuple_assign(
        corpus.trees[faults_anchor], "KNOWN_SITES"
    )
    for site in _XHOST_SITES:
        if site not in sites:
            yield Finding(
                rule="host-locality",
                path=faults_anchor,
                line=sites_line or 1,
                message=f"cross-host fleet site {site!r} is not "
                f"registered in KNOWN_SITES",
                hint="register it — the chaos blanket "
                "(tests/test_chaos.py) exercises every registered "
                "site, which is what proves the pid-free takeover "
                "ladders survive injected faults",
            )


# ----------------------------------------- rule: thread-confinement

# device/dispatch entry points: work only roles holding the "device"
# effect grant may perform (a device call from an ungranted thread
# races the mesh dispatch and voids the single-dispatcher ordering
# argument the byte-identity proofs rest on)
_DEVICE_CALLS = {
    "device_put", "block_until_ready", "sharded_pipeline",
    "presharded_pipeline", "start_fetch", "dispatch_chunk",
    "materialize", "materialise", "fetch_outputs",
}

# durable-state moves requiring the "durable" grant: per-chunk
# checkpoint marks and durable writes — exactly-once resume is proven
# over declared-role commit order, and an ungranted thread's mark
# would commit a chunk its owner has not finished
_DURABLE_MOVE_CALLS = {
    "mark", "save", "write_durable", "replace_durable", "rewrite_from",
}

# flock'd journal transactions require the "journal" grant (the serve
# fleet's txn seam; rule 10 checks what happens INSIDE the txn body,
# this rule checks WHO may open one)
_JOURNAL_CALLS = {"_txn", "txn"}


def _thread_roles(corpus: Corpus):
    """(knobs_path, THREAD_ROLES dict) read FROM THE CORPUS — never
    imported, so fixture corpora declare their own miniature
    registries. (None, None) when runtime/knobs.py is absent;
    (path, None) when present but the literal is unreadable."""
    path = corpus.find("runtime/knobs.py")
    if path is None:
        return None, None
    roles = literal_assign(corpus.trees[path], "THREAD_ROLES")
    if not isinstance(roles, dict) or not roles:
        return path, None
    return path, roles


@register(
    "thread-confinement",
    "every declared thread role's transitive call scope stays inside "
    "its allowed effects, shared structures and locks",
)
def check_thread_confinement(corpus: Corpus) -> Iterator[Finding]:
    """The declared thread-confinement model: ``THREAD_ROLES`` in
    runtime/knobs.py maps each thread-entry function (xfer/drain pool
    bodies, the ``dut-ingest`` producer, heartbeat, the serve
    watchdog/workers — PR 17's ingest-only rule is now the producer
    row) to its allowed effects, and this rule walks each entry's
    transitive same-file call scope against the row:

    (a) a device/dispatch call without the "device" grant, a durable
        state move without "durable", a journal txn without "journal";
    (b) touching a structure another role declared (the per-module
        union of ``shared`` names is the watched set) without
        declaring it, or touching a declared one outside its declared
        ``with <lock>:`` body (lock "" = self-synchronizing);
    (c) for roles with a declared ``handoff`` queue: putting to any
        other queue bypasses the one audited seam.

    Rename protection: a registry row whose entry function is gone
    while its thread-name marker is still in the module has renamed
    the anchor out from under the rule — a finding, not a skip. A
    corpus with no THREAD_ROLES at all owes nothing (pre-registry
    fixtures), unless a file still references the registry name."""
    knobs_path, roles = _thread_roles(corpus)
    if roles is None:
        if knobs_path is not None and (
            "THREAD_ROLES" in corpus.sources[knobs_path]
        ):
            yield Finding(
                rule="thread-confinement",
                path=knobs_path,
                line=1,
                message="THREAD_ROLES is present but not a readable "
                "literal dict",
                hint="keep the registry a PURE literal — the rule reads "
                "it from the parsed corpus, never by import",
            )
            return
        # pre-registry corpora owe nothing; but a tree that still
        # NAMES the registry while the literal is gone has deleted the
        # model out from under its machinery
        for path in sorted(corpus.trees):
            if path == knobs_path:
                continue
            if "THREAD_ROLES" in corpus.sources[path]:
                yield Finding(
                    rule="thread-confinement",
                    path=path,
                    line=1,
                    message="THREAD_ROLES is referenced but "
                    "runtime/knobs.py declares no readable literal",
                    hint="restore the THREAD_ROLES literal in "
                    "runtime/knobs.py — the thread model must stay "
                    "declared",
                )
        return

    # per-module watched set: the union of every role's shared names —
    # what ANY role owns, every other role in that module must declare
    # before touching
    watched_by_module: dict[str, set[str]] = {}
    for role, row in roles.items():
        if not isinstance(row, dict):
            continue
        for pair in row.get("shared", ()):
            watched_by_module.setdefault(
                str(row.get("module", "")), set()
            ).add(str(pair[0]))

    for role in sorted(roles):
        row = roles[role]
        if not (
            isinstance(row, dict)
            and isinstance(row.get("module"), str)
            and row.get("module")
            and "entry" in row
        ):
            yield Finding(
                rule="thread-confinement",
                path=knobs_path,
                line=1,
                message=f"THREAD_ROLES[{role!r}] is malformed "
                f"(needs module/entry/may/shared)",
                hint="see runtime/knobs.py's field contract",
            )
            continue
        mod_path = corpus.find(row["module"])
        if mod_path is None:
            continue  # fixture corpora may carry a module subset
        entry = str(row["entry"])
        if not entry:
            continue  # the main loop: an ownership row, not walked
        tree = corpus.trees[mod_path]
        defs = function_defs(tree)
        marker = str(row.get("marker", ""))
        if entry not in defs:
            if marker and marker in corpus.sources[mod_path]:
                yield Finding(
                    rule="thread-confinement",
                    path=mod_path,
                    line=1,
                    message=f"thread marker {marker!r} present but the "
                    f"declared {role!r} entry {entry}() is gone",
                    hint="keep the thread body in a function named as "
                    "declared in THREAD_ROLES — it anchors the "
                    "confinement walk",
                )
            continue
        may = {str(m) for m in row.get("may", ())}
        allowed = {
            str(p[0]): (str(p[1]) if len(p) > 1 else "")
            for p in row.get("shared", ())
        }
        handoff = str(row.get("handoff", ""))
        watched = watched_by_module.get(row["module"], set())
        for fn in reachable_functions(defs, entry):
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    name = call_name(node)
                    callee = expr_path(node.func) or name
                    if (
                        name in _DEVICE_CALLS or callee.startswith("jax.")
                    ) and "device" not in may:
                        yield Finding(
                            rule="thread-confinement",
                            path=mod_path,
                            line=node.lineno,
                            message=f"device/dispatch call {callee}() in "
                            f"the {role!r} thread scope ({fn.name}) "
                            f"without the 'device' grant",
                            hint="device work belongs to roles declaring "
                            "'device' in THREAD_ROLES (single-"
                            "dispatcher ordering)",
                        )
                    elif name in _DURABLE_MOVE_CALLS and "durable" not in may:
                        yield Finding(
                            rule="thread-confinement",
                            path=mod_path,
                            line=node.lineno,
                            message=f"durable state move {callee}() in "
                            f"the {role!r} thread scope ({fn.name}) "
                            f"without the 'durable' grant",
                            hint="checkpoint marks / durable writes "
                            "commit only from roles declaring 'durable' "
                            "— anything else breaks exactly-once resume",
                        )
                    elif name in _JOURNAL_CALLS and "journal" not in may:
                        yield Finding(
                            rule="thread-confinement",
                            path=mod_path,
                            line=node.lineno,
                            message=f"journal txn {callee}() in the "
                            f"{role!r} thread scope ({fn.name}) without "
                            f"the 'journal' grant",
                            hint="only roles declaring 'journal' may "
                            "open the flock'd journal transaction",
                        )
                    elif (
                        handoff
                        and isinstance(node.func, ast.Attribute)
                        and node.func.attr in ("put", "put_nowait")
                    ):
                        recv = expr_path(node.func.value) or ""
                        if not recv.endswith(handoff):
                            yield Finding(
                                rule="thread-confinement",
                                path=mod_path,
                                line=node.lineno,
                                message=f"{role!r} thread puts to "
                                f"{recv or '?'!r} — not its declared "
                                f"handoff queue ({handoff})",
                                hint="the declared handoff queue is the "
                                "role's only legal output channel",
                            )
                elif isinstance(node, ast.Name) and node.id in watched:
                    if node.id not in allowed:
                        yield Finding(
                            rule="thread-confinement",
                            path=mod_path,
                            line=node.lineno,
                            message=f"shared structure {node.id!r} "
                            f"touched in the {role!r} thread scope "
                            f"({fn.name}) but not declared in its "
                            f"THREAD_ROLES row",
                            hint="declare the (structure, lock) pair in "
                            "the role's shared list — or keep the "
                            "structure out of that thread's lane",
                        )
                    else:
                        lock = allowed[node.id]
                        if lock and not inside_named_lock(node, lock):
                            yield Finding(
                                rule="thread-confinement",
                                path=mod_path,
                                line=node.lineno,
                                message=f"shared structure {node.id!r} "
                                f"touched in the {role!r} thread scope "
                                f"({fn.name}) outside its declared "
                                f"lock ({lock})",
                                hint=f"wrap the access in "
                                f"`with {lock}:` — the registry says "
                                f"that lock guards this structure",
                            )


# ------------------------------------------------- rule: knob-taint

# the canonical surface vocabulary (mirrored from runtime/knobs.py
# SURFACES; the corpus declaration wins when present)
_KNOWN_SURFACES = (
    "fingerprint", "spec_signature", "provenance", "job_config",
    "streaming_only",
)


def _knob_table(corpus: Corpus):
    """(knobs_path, KNOB_TABLE dict, assign lineno) read FROM THE
    CORPUS — same contract as :func:`_thread_roles`."""
    path = corpus.find("runtime/knobs.py")
    if path is None:
        return None, None, 0
    tree = corpus.trees[path]
    table = literal_assign(tree, "KNOB_TABLE")
    line = 1
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "KNOB_TABLE"
                for t in node.targets
            )
        ):
            line = node.lineno
            break
    if not isinstance(table, dict) or not table:
        return path, None, line
    return path, table, line


def _fn_scan(fn: ast.AST):
    """(name_lines, literal_lines, kwarg_lines) for one function body:
    every Name id, string literal, and keyword-argument name, each
    mapped to its first line — the evidence a knob 'reaches' a
    determinism-surface constructor."""
    names: dict[str, int] = {}
    lits: dict[str, int] = {}
    kwargs: dict[str, int] = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Name):
            names.setdefault(node.id, node.lineno)
        s = str_const(node)
        if s is not None:
            lits.setdefault(s, node.lineno)
        if isinstance(node, ast.Call):
            for kw in node.keywords or ():
                if kw.arg:
                    kwargs.setdefault(kw.arg, node.lineno)
    return names, lits, kwargs


def _imports_knobs(tree: ast.Module) -> bool:
    """Does this module import the knob registry (``from ...runtime
    import knobs`` / ``from ...runtime.knobs import ...``)? The
    evidence that a surface constructor is table-driven."""
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.endswith("runtime.knobs") or mod.endswith(".knobs"):
                return True
            if mod.endswith("runtime") and any(
                a.name == "knobs" for a in node.names
            ):
                return True
        elif isinstance(node, ast.Import):
            if any(a.name.endswith(".knobs") for a in node.names):
                return True
    return False


@register(
    "knob-taint",
    "every execution knob is declared in runtime/knobs.py and reaches "
    "exactly its declared determinism surfaces",
)
def check_knob_taint(corpus: Corpus) -> Iterator[Finding]:
    """The knob registry model-check: ``KNOB_TABLE`` declares every
    execution knob's class (semantic | scheduling) and its membership
    in each determinism surface; this rule walks the surface
    constructors against it:

    (a) table sanity — every row's class and surfaces are from the
        declared vocabulary;
    (b) the checkpoint fingerprint (runtime/stream.py
        ``_fingerprint``): a knob reaching it must declare the
        ``fingerprint`` surface (for a scheduling knob that is the
        taint this rule exists to catch — resumes would refuse
        byte-identical work); a declared knob must actually reach it
        (by parameter name, or via ``dataclasses.asdict`` for
        ``via: params`` knobs);
    (c) the compile identity (serve/job.py ``spec_signature``): config
        keys used == keys declared, both directions;
    (d) the provenance line (serve/job.py ``serve_provenance``): any
        knob special-cased by literal while its row excludes the
        ``provenance`` surface is a hand-rolled exclusion — surface
        membership lives in the registry, not in the constructor;
    (e) the job config (serve/job.py ``CONFIG_DEFAULTS``): a literal
        dict must match the declared ``job_config`` set exactly; a
        derived one must come from the registry (knobs import);
    (f) the CLI's resolution closed world: every ``opt("...")``
        literal in cli/main.py is a declared knob, and the
        streaming-only refusals are table-driven;
    (g) coverage pin (TRANSITIONS-style): every declared scheduling
        job knob is exercised by name in the linted test anchors —
        the byte-identity matrix is the proof scheduling knobs are
        byte-neutral, so an unexercised one is an unproved claim."""
    knobs_path, table, table_line = _knob_table(corpus)
    if table is None:
        if knobs_path is not None and (
            "KNOB_TABLE" in corpus.sources[knobs_path]
        ):
            yield Finding(
                rule="knob-taint",
                path=knobs_path,
                line=table_line,
                message="KNOB_TABLE is present but not a readable "
                "literal dict",
                hint="keep the registry a PURE literal — the rule reads "
                "it from the parsed corpus, never by import",
            )
            return
        for path in sorted(corpus.trees):
            if path == knobs_path:
                continue
            if "KNOB_TABLE" in corpus.sources[path]:
                yield Finding(
                    rule="knob-taint",
                    path=path,
                    line=1,
                    message="KNOB_TABLE is referenced but "
                    "runtime/knobs.py declares no readable literal",
                    hint="restore the KNOB_TABLE literal in "
                    "runtime/knobs.py — the knob surfaces must stay "
                    "declared",
                )
        return

    surfaces_vocab = set(_KNOWN_SURFACES)
    declared_vocab, _ = str_tuple_assign(
        corpus.trees[knobs_path], "SURFACES"
    )
    if declared_vocab:
        surfaces_vocab = set(declared_vocab)

    # (a) table sanity
    rows: dict[str, dict] = {}
    for name in table:
        row = table[name]
        if not isinstance(row, dict) or row.get("class") not in (
            "semantic", "scheduling"
        ):
            yield Finding(
                rule="knob-taint",
                path=knobs_path,
                line=table_line,
                message=f"knob {name!r} has no valid class "
                f"(semantic | scheduling)",
                hint="every knob declares its class — it decides which "
                "surfaces the knob may legally reach",
            )
            continue
        bad = set(row.get("surfaces", ())) - surfaces_vocab
        if bad:
            yield Finding(
                rule="knob-taint",
                path=knobs_path,
                line=table_line,
                message=f"knob {name!r} declares unknown surface(s) "
                f"{sorted(bad)}",
                hint=f"the surface vocabulary is {sorted(surfaces_vocab)}",
            )
            continue
        rows[name] = row

    def surf(name: str) -> set:
        return set(rows[name].get("surfaces", ()))

    # (b) the checkpoint fingerprint
    stream_path = corpus.find("runtime/stream.py")
    fp_fn = None
    if stream_path is not None:
        fp_fn = function_defs(corpus.trees[stream_path]).get("_fingerprint")
    if fp_fn is not None:
        names, lits, kwargs = _fn_scan(fp_fn)
        asdict_line = 0
        for node in ast.walk(fp_fn):
            if isinstance(node, ast.Call) and call_name(node) == "asdict":
                asdict_line = node.lineno
                break
        for name in rows:
            key = rows[name].get("stream_kwarg") or name
            declared = "fingerprint" in surf(name)
            at = names.get(key) or lits.get(key) or kwargs.get(key)
            if declared and rows[name].get("via") == "params":
                if not asdict_line:
                    yield Finding(
                        rule="knob-taint",
                        path=stream_path,
                        line=fp_fn.lineno,
                        message=f"knob {name!r} declares the fingerprint "
                        f"surface via params but _fingerprint has no "
                        f"dataclasses.asdict() evidence",
                        hint="via:'params' knobs reach the fingerprint "
                        "through asdict(GroupingParams/ConsensusParams) "
                        "— keep that call, or redeclare the route",
                    )
            elif declared and at is None:
                yield Finding(
                    rule="knob-taint",
                    path=stream_path,
                    line=fp_fn.lineno,
                    message=f"knob {name!r} declares the fingerprint "
                    f"surface but never reaches _fingerprint",
                    hint="thread it through _fingerprint (or drop the "
                    "surface from its KNOB_TABLE row) — a declared-but-"
                    "absent semantic knob lets resume splice shards "
                    "computed under different semantics",
                )
            elif not declared and at is not None:
                if rows[name]["class"] == "scheduling":
                    yield Finding(
                        rule="knob-taint",
                        path=stream_path,
                        line=at,
                        message=f"scheduling knob {name!r} taints the "
                        f"checkpoint fingerprint",
                        hint="scheduling knobs are byte-neutral by "
                        "contract — fingerprinting one makes resume "
                        "refuse byte-identical work; drop it from "
                        "_fingerprint",
                    )
                else:
                    yield Finding(
                        rule="knob-taint",
                        path=stream_path,
                        line=at,
                        message=f"knob {name!r} reaches _fingerprint but "
                        f"does not declare the fingerprint surface",
                        hint="declare the surface in its KNOB_TABLE row "
                        "— the registry states shipped behaviour",
                    )

    # (c)+(d)+(e): the serve-side surfaces
    job_path = corpus.find("serve/job.py")
    if job_path is not None:
        job_tree = corpus.trees[job_path]
        job_defs = function_defs(job_tree)
        sig_fn = job_defs.get("spec_signature")
        if sig_fn is not None:
            _, lits, kwargs = _fn_scan(sig_fn)
            for name in rows:
                declared = "spec_signature" in surf(name)
                at = lits.get(name) or kwargs.get(name)
                if declared and at is None:
                    yield Finding(
                        rule="knob-taint",
                        path=job_path,
                        line=sig_fn.lineno,
                        message=f"knob {name!r} declares the "
                        f"spec_signature surface but spec_signature "
                        f"never reads it",
                        hint="geometry-bearing knobs must join the "
                        "compile identity — two jobs differing in one "
                        "must not share XLA programs",
                    )
                elif at is not None and not declared:
                    yield Finding(
                        rule="knob-taint",
                        path=job_path,
                        line=at,
                        message=f"knob {name!r} joins spec_signature "
                        f"without declaring the surface",
                        hint="declare spec_signature in its KNOB_TABLE "
                        "row — undeclared signature members split the "
                        "compile cache silently",
                    )
        prov_fn = job_defs.get("serve_provenance")
        if prov_fn is not None:
            _, lits, kwargs = _fn_scan(prov_fn)
            for name in rows:
                at = lits.get(name) or kwargs.get(name)
                if at is not None and "provenance" not in surf(name):
                    yield Finding(
                        rule="knob-taint",
                        path=job_path,
                        line=at,
                        message=f"serve_provenance special-cases knob "
                        f"{name!r}, whose row excludes the provenance "
                        f"surface",
                        hint="surface membership is declared in "
                        "runtime/knobs.py — serve_provenance iterates "
                        "the registry, it does not hand-roll knob "
                        "exclusions",
                    )
        cd = literal_assign(job_tree, "CONFIG_DEFAULTS")
        declared_jc = {n for n in rows if "job_config" in surf(n)}
        if isinstance(cd, dict):
            extra = set(cd) - declared_jc
            missing = declared_jc - set(cd)
            for name in sorted(extra):
                yield Finding(
                    rule="knob-taint",
                    path=job_path,
                    line=1,
                    message=f"CONFIG_DEFAULTS carries {name!r}, which "
                    f"does not declare the job_config surface",
                    hint="declare job_config in its KNOB_TABLE row (or "
                    "drop the key)",
                )
            for name in sorted(missing):
                yield Finding(
                    rule="knob-taint",
                    path=job_path,
                    line=1,
                    message=f"knob {name!r} declares job_config but "
                    f"CONFIG_DEFAULTS lacks the key",
                    hint="derive CONFIG_DEFAULTS from the registry "
                    "(knobs.job_config_defaults()) so the two cannot "
                    "drift",
                )
        elif "CONFIG_DEFAULTS" in corpus.sources[job_path] and not (
            _imports_knobs(job_tree)
        ):
            yield Finding(
                rule="knob-taint",
                path=job_path,
                line=1,
                message="CONFIG_DEFAULTS is neither a literal dict nor "
                "derived from the knob registry",
                hint="derive it with knobs.job_config_defaults() — the "
                "registry is the single declaration",
            )

    # (f) the CLI's closed world
    cli_path = corpus.find("cli/main.py")
    if cli_path is not None:
        cli_tree = corpus.trees[cli_path]
        for node in ast.walk(cli_tree):
            if not (isinstance(node, ast.Call) and call_name(node) == "opt"):
                continue
            if not node.args:
                continue
            lit = str_const(node.args[0])
            if lit is not None and lit not in table:
                yield Finding(
                    rule="knob-taint",
                    path=cli_path,
                    line=node.lineno,
                    message=f"opt({lit!r}) resolves an undeclared knob",
                    hint="add a KNOB_TABLE row in runtime/knobs.py — "
                    "adding a knob IS editing the registry; the linter "
                    "enforces the rest",
                )
        streaming_only = [
            n for n in rows if "streaming_only" in surf(n)
        ]
        if streaming_only and not _imports_knobs(cli_tree):
            yield Finding(
                rule="knob-taint",
                path=cli_path,
                line=1,
                message="streaming-only knobs are declared but "
                "cli/main.py does not resolve refusals through the "
                "registry",
                hint="route the whole-file refusals through "
                "knobs.streaming_only_keys() — hand-copied refusal "
                "blocks are how --trace got silently dropped once",
            )

    # (g) coverage pin: scheduling job knobs must appear in the linted
    # test anchors (the byte-identity matrix is the proof they are
    # byte-neutral)
    test_paths = [p for p in corpus.trees if p.startswith("tests/")]
    if test_paths:
        exercised: set[str] = set()
        for p in test_paths:
            for fn_node in [corpus.trees[p]]:
                names, lits, kwargs = _fn_scan(fn_node)
                exercised |= set(names) | set(lits) | set(kwargs)
        for name in sorted(
            n for n in rows
            if rows[n]["class"] == "scheduling" and "job_config" in surf(n)
        ):
            flag = str(rows[name].get("flag", ""))
            if name in exercised or (flag and flag in exercised):
                continue
            yield Finding(
                rule="knob-taint",
                path=knobs_path,
                line=table_line,
                message=f"scheduling knob {name!r} has no byte-identity "
                f"exercise in the linted test anchors",
                hint="add it to the byte-identity matrix (tests/"
                "test_knobs.py SCHEDULING_MATRIX) — an unexercised "
                "scheduling knob's byte-neutrality is an unproved claim",
            )


# ------------------------------------------- rule: kernel-cost-registry

def _dict_str_keys(tree: ast.Module, name: str) -> tuple[set[str], int]:
    """Keys of a module-level ``NAME = {"a": <anything>, ...}`` dict
    literal — the cost registry's shape (values are function refs, so
    engine.str_dict_assign's tuple-valued contract does not fit).
    Returns (set(), 0) when missing or not all-literal-keyed."""
    for node in tree.body:
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id == name
            and isinstance(node.value, ast.Dict)
        ):
            continue
        keys = [str_const(k) for k in node.value.keys if k is not None]
        if keys and all(k is not None for k in keys):
            return {k for k in keys if k is not None}, node.lineno
    return set(), 0


def _method_literals(tree: ast.Module) -> Iterator[tuple[str, int]]:
    """Every string literal a kernels/ module treats as an ssc-method
    name: comparisons against a ``method`` variable (``method ==
    "matmul"``, ``method in ("blockseg", "runsum")``) and the default
    of a ``method=`` parameter. These are the literals that select a
    kernel path — exactly the set the FLOP-cost registry must cover."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Compare):
            if not (
                isinstance(node.left, ast.Name)
                and node.left.id == "method"
            ):
                continue
            for comp in node.comparators:
                lit = str_const(comp)
                if lit is not None:
                    yield lit, node.lineno
                elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                    for e in comp.elts:
                        lit = str_const(e)
                        if lit is not None:
                            yield lit, node.lineno
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            a = node.args
            params = a.posonlyargs + a.args
            defaults = a.defaults
            # defaults align to the TAIL of the parameter list
            for param, default in zip(params[len(params) - len(defaults):],
                                      defaults):
                if param.arg == "method":
                    lit = str_const(default)
                    if lit is not None:
                        yield lit, node.lineno
            for param, default in zip(a.kwonlyargs, a.kw_defaults):
                if param.arg == "method" and default is not None:
                    lit = str_const(default)
                    if lit is not None:
                        yield lit, node.lineno


@register(
    "kernel-cost-registry",
    "every kernel method literal has a FLOP-cost entry and every dev "
    "record field is registered",
)
def check_kernel_cost_registry(corpus: Corpus) -> Iterator[Finding]:
    """The device ledger's honesty depends on two registries staying
    closed over their call sites: a kernel method with no entry in
    ``ops.pipeline.SSC_METHOD_COSTS`` makes ``analytic_flops`` raise on
    a healthy run (the executor emits FLOPs for every dispatch), and a
    ``dev(...)`` field outside ``telemetry.trace.KNOWN_DEV_FIELDS``
    fails the capture validator only at runtime, with a trace flag set
    — the same too-late drift class the phase-registry rule pins for
    spans. Both directions: an unregistered literal fires at its call
    site; a cost entry no kernel ever selects is a dead registry row."""
    pipe_path = corpus.find("ops/pipeline.py")
    trace_path = corpus.find("telemetry/trace.py")
    costs: set[str] = set()
    costs_line = 1
    if pipe_path is not None:
        costs, costs_line = _dict_str_keys(
            corpus.trees[pipe_path], "SSC_METHOD_COSTS"
        )
    dev_fields: list[str] = []
    if trace_path is not None:
        dev_fields, _ = str_tuple_assign(
            corpus.trees[trace_path], "KNOWN_DEV_FIELDS"
        )

    seen_methods: set[str] = set()
    for path in corpus.package_paths():
        if "kernels/" in path and costs:
            for lit, line in _method_literals(corpus.trees[path]):
                seen_methods.add(lit)
                if lit not in costs:
                    yield Finding(
                        rule="kernel-cost-registry",
                        path=path,
                        line=line,
                        message=f"kernel method {lit!r} has no registered "
                        f"FLOP cost",
                        hint="register a cost function under that key in "
                        "ops.pipeline.SSC_METHOD_COSTS — analytic_flops "
                        "raises on unregistered methods and every "
                        "dispatch is FLOP-ledgered",
                    )
        if dev_fields and path != trace_path:
            for node in ast.walk(corpus.trees[path]):
                if not isinstance(node, ast.Call):
                    continue
                if call_name(node) != "dev":
                    continue
                for kw in node.keywords or ():
                    # chunk/lane are envelope args of the recorder
                    # method, not ledger fields
                    if kw.arg in (None, "chunk", "lane"):
                        continue
                    if kw.arg not in dev_fields:
                        yield Finding(
                            rule="kernel-cost-registry",
                            path=path,
                            line=node.lineno,
                            message=f"dev record field {kw.arg!r} is not "
                            f"registered",
                            hint="register it in telemetry.trace."
                            "KNOWN_DEV_FIELDS (and the dev schema golden "
                            "+ ARCHITECTURE.md) — the validator rejects "
                            "unregistered dev fields",
                        )

    # dead-registry direction: a cost entry nothing in kernels/ can
    # select will never be exercised and hides geometry drift
    if costs and seen_methods and pipe_path is not None:
        for key in sorted(costs - seen_methods):
            yield Finding(
                rule="kernel-cost-registry",
                path=pipe_path,
                line=costs_line,
                message=f"FLOP cost registered for {key!r} but no kernel "
                f"selects that method",
                hint="prune the SSC_METHOD_COSTS entry or wire the "
                "method into kernels/",
            )
