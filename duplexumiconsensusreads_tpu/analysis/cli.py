"""dutlint CLI: run the invariant rules over the repo's linted set.

Default file set: the whole ``duplexumiconsensusreads_tpu`` package,
every ``tools/*.py`` script, and the test-side registry anchors
(``tests/test_chaos.py`` for fault-site coverage,
``tests/test_telemetry.py`` for the seconds-keys golden,
``tests/test_serve.py`` for serving-site lease/takeover coverage) —
which are also linted themselves.

Exit status: 0 when clean (allowlisted findings don't count, but are
listed with their reasons under -v), 1 on any non-allowlisted finding,
2 on usage errors. ``--json`` emits a machine-readable report (rule,
file, line, message per finding) for CI and editors; ``--rule ID``
(repeatable) runs/bisects single passes; ``--strict`` — the CI gate's
mode (tools/ci_check.sh) — additionally fails default-set runs whose
allowlist carries stale entries. ``--since REV`` is the fast local
loop: the FULL default corpus still loads (the cross-file registries
need it), but only findings in files changed vs the git rev are
reported — CI keeps the whole-tree ``--strict`` gate.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

from duplexumiconsensusreads_tpu.analysis.allowlist import ALLOWLIST
from duplexumiconsensusreads_tpu.analysis.engine import (
    RULES,
    load_corpus,
    run_lint,
)

PACKAGE = "duplexumiconsensusreads_tpu"
# test files the cross-file rules anchor on; linted like everything else
TEST_ANCHORS = (
    "tests/test_chaos.py",
    "tests/test_telemetry.py",
    "tests/test_serve.py",
    "tests/test_knobs.py",
)


def repo_root() -> str:
    """The directory containing the package (works from a checkout;
    the console-script entry resolves through the installed package)."""
    import duplexumiconsensusreads_tpu as pkg

    return os.path.dirname(os.path.dirname(os.path.abspath(pkg.__file__)))


def default_targets(root: str) -> list[str]:
    rels: list[str] = []
    pkg_dir = os.path.join(root, PACKAGE)
    for dirpath, dirnames, filenames in os.walk(pkg_dir):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in sorted(filenames):
            if fn.endswith(".py"):
                rels.append(
                    os.path.relpath(os.path.join(dirpath, fn), root)
                )
    tools_dir = os.path.join(root, "tools")
    if os.path.isdir(tools_dir):
        for fn in sorted(os.listdir(tools_dir)):
            if fn.endswith(".py"):
                rels.append(os.path.join("tools", fn))
    for anchor in TEST_ANCHORS:
        if os.path.exists(os.path.join(root, anchor)):
            rels.append(anchor)
    return rels


def changed_since(root: str, rev: str) -> set[str] | None:
    """Repo-relative paths changed vs ``rev``: committed diffs plus
    worktree edits plus untracked files — everything the fast local
    loop might have touched. None (usage error) when git fails — an
    unknown rev must not silently lint nothing."""
    try:
        diff = subprocess.run(
            ["git", "-C", root, "diff", "--name-only", rev, "--"],
            capture_output=True, text=True,
        )
        if diff.returncode != 0:
            return None
        untracked = subprocess.run(
            ["git", "-C", root, "ls-files", "--others",
             "--exclude-standard"],
            capture_output=True, text=True,
        )
        if untracked.returncode != 0:
            return None
    except OSError:
        return None
    return {
        line.strip()
        for out in (diff.stdout, untracked.stdout)
        for line in out.splitlines()
        if line.strip()
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="dutlint",
        description="AST-based invariant linter (clocks, durability, "
        "fault sites, phase registries, lock discipline, hook guards, "
        "and the serving fleet's protocol model: state machine, txn/"
        "fence dominance, exception contracts)",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="repo-relative files to lint (default: package + tools/ + "
        "test anchors)",
    )
    ap.add_argument("--root", default=None, help="repo root (default: "
                    "the checkout containing the package)")
    ap.add_argument("--rule", action="append", dest="rules", metavar="ID",
                    help="run only this rule (repeatable)")
    ap.add_argument(
        "--since", metavar="REV", default=None,
        help="incremental mode: load the full default corpus (the "
        "cross-file registries need it) but report only findings in "
        "files changed vs this git rev (committed + worktree + "
        "untracked); CI keeps the whole-tree --strict gate",
    )
    ap.add_argument("--json", action="store_true", help="JSON report")
    ap.add_argument(
        "--strict", action="store_true",
        help="also exit 1 on stale allowlist entries (default-set runs "
        "only — an explicit file subset legitimately misses most "
        "entries); the CI gate runs with this on",
    )
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also list allowlist-suppressed findings")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rid in sorted(RULES):
            print(f"{rid:<22} {RULES[rid].title}")
        return 0

    root = os.path.abspath(args.root) if args.root else repo_root()
    if args.since and args.paths:
        print("dutlint: --since and explicit paths are mutually "
              "exclusive (--since picks the file set itself)",
              file=sys.stderr)
        return 2
    rels = args.paths or default_targets(root)
    if args.rules:
        bad = [r for r in args.rules if r not in RULES]
        if bad:
            print(f"dutlint: unknown rule(s): {', '.join(bad)}",
                  file=sys.stderr)
            return 2
    changed: set[str] | None = None
    if args.since:
        changed = changed_since(root, args.since)
        if changed is None:
            print(f"dutlint: --since {args.since}: not a resolvable "
                  f"git rev in {root}", file=sys.stderr)
            return 2
    try:
        corpus = load_corpus(root, rels)
    except OSError as e:
        print(f"dutlint: {e}", file=sys.stderr)
        return 2
    result = run_lint(corpus, ALLOWLIST, only_rules=args.rules)
    if changed is not None:
        # the registries were read from the FULL corpus above; only the
        # reporting narrows. A finding in an unchanged file still means
        # the tree is dirty — but that is CI's whole-tree job, not the
        # fast local loop's.
        result.findings = [f for f in result.findings if f.path in changed]
        result.suppressed = [
            (f, a) for f, a in result.suppressed if f.path in changed
        ]
    # --strict folds allowlist staleness into the exit status, but only
    # against the full default set (see the warning path below);
    # --since is a subset view, so staleness stays out of its verdict
    stale_fails = bool(
        args.strict and not args.paths and not args.since
        and result.unused_allowlist
    )

    if args.json:
        ok = result.ok and not stale_fails
        print(json.dumps({
            "root": root,
            "n_files": len(corpus.trees) + len(corpus.parse_failures),
            "findings": [vars(f) for f in result.findings],
            "suppressed": [
                {**vars(f), "reason": a.reason}
                for f, a in result.suppressed
            ],
            "unused_allowlist": [vars(a) for a in result.unused_allowlist],
            "ok": ok,
        }, indent=2))
        return 0 if ok else 1

    for f in result.findings:
        print(f.format())
    if args.verbose:
        for f, a in result.suppressed:
            print(f"allowed: {f.format()}\n         reason: {a.reason}")
    if not args.paths and not args.since:
        # staleness is only meaningful against the full default set: an
        # explicit file subset legitimately misses most entries. Stale
        # suppressions are warnings here (failures under --strict — the
        # CI gate); the tier-1 gate (tests/test_lint.py) also forces
        # pruning.
        severity = "error" if args.strict else "warning"
        for a in result.unused_allowlist:
            print(
                f"dutlint: {severity}: unused allowlist entry "
                f"({a.rule}, {a.path}) — prune it",
                file=sys.stderr,
            )
    n_files = len(corpus.trees) + len(corpus.parse_failures)
    if result.ok and not stale_fails:
        print(
            f"dutlint: OK — {n_files} files, "
            f"{len(RULES) if not args.rules else len(args.rules)} rules, "
            f"{len(result.suppressed)} allowlisted"
        )
        return 0
    if result.ok and stale_fails:
        print(
            f"dutlint: {len(result.unused_allowlist)} stale allowlist "
            f"entr(y/ies) under --strict",
            file=sys.stderr,
        )
        return 1
    print(
        f"dutlint: {len(result.findings)} finding(s) in {n_files} files "
        f"({len(result.suppressed)} allowlisted)",
        file=sys.stderr,
    )
    return 1


if __name__ == "__main__":
    sys.exit(main())
