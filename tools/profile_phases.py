"""Decompose the fused-pipeline step time on the real chip: time the
bench geometry under spec variants that drop one stage each —
exact grouping (no Hamming/closure), no cycle error model (one ssc
pass instead of two) — to see which device stage owns the wall.

Run: python tools/profile_phases.py
     python tools/profile_phases.py --report report.json
       (render a `call --report` / streaming RunReport JSON as
        overlapped busy-time vs wall columns; any stage whose busy
        time exceeds wall x its pool size is flagged BUSY>WALL — an
        accounting-bug canary, since that is impossible with honest
        monotonic clocks)

Journal (v5e-1, axon tunnel, 2026-07-30, 527k reads, capacity 2048):
  full config5 (adj+cycle)   0.211s   2.25M reads/s
  no error model (adj)       0.189s   2.52M   -> 2nd ssc pass ~ 10%
  exact grouping + cycle     0.199s   2.39M   -> Hamming+closure ~ 6%
  exact, no error model      0.183s   2.59M
No single device stage dominates; the bulk is the core ssc GEMM +
contributions elementwise + fixed per-step costs.

Related measurements feeding benchmark.py decisions:
- Sync discipline: fetching every class's output paid a tunnel RTT
  each; ONE fetch of the final program suffices (TPUs execute
  programs in order) — +7% step throughput; bench.py now does this.
- Class granularity: merging the (255-bucket, u_max 512) class into
  the (1-bucket, u_max 1024) geometry = ONE launch but 1.5x SLOWER —
  the u^3 closure padding dwarfs the saved launch; the pow2
  unique-count classing stays.
- Capacity sweep (bench.py, same workload): 1024 -> 2.24M reads/s
  (mfu .027), 2048 -> 2.45M (mfu .060)  <-- default, 4096 -> 2.32M
  (mfu .141), 8192 -> 1.82M (mfu .336). MFU rises with capacity only
  because the u^3 closure burns more padded FLOPs per read — analytic
  MFU is NOT the objective; reads/s is.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def report_busy_wall(path: str) -> int:
    """Print the overlapped busy-vs-wall table for a RunReport JSON
    (from `call --report`). Exit status 1 when any stage's busy time
    exceeds wall x pool — the accounting-bug canary for CI.

    Tolerant of OLDER report shapes by design: pre-pipelined-drain
    reports lack main_loop_stall / drain_utilization / n_drain_workers
    (and whole-file reports lack "total"); every absent field renders
    as its neutral default instead of a KeyError — this tool is how
    historical captures get re-read, so it must accept them all."""
    from duplexumiconsensusreads_tpu.runtime.executor import busy_wall_table

    with open(path) as f:
        rep = json.load(f)
    if not isinstance(rep, dict) or not isinstance(rep.get("seconds", {}), dict):
        print(f"{path}: not a RunReport JSON (no seconds dict)", file=sys.stderr)
        return 1
    dw = rep.get("n_drain_workers", 1)
    if not isinstance(dw, int) or isinstance(dw, bool):
        dw = 1
    lines, bugs = busy_wall_table(
        rep.get("seconds", {}) or {}, drain_workers=max(dw, 1)
    )
    for ln in lines:
        print(ln)
    if bugs:
        print(
            f"ACCOUNTING BUG: stage(s) {', '.join(bugs)} report more busy "
            f"time than wall x pool allows",
            file=sys.stderr,
        )
        return 1
    return 0


def main() -> None:
    import jax

    from duplexumiconsensusreads_tpu.bucketing import build_buckets, stack_buckets
    from duplexumiconsensusreads_tpu.parallel import make_mesh
    from duplexumiconsensusreads_tpu.parallel.sharded import (
        presharded_pipeline,
        shard_stacked,
    )
    from duplexumiconsensusreads_tpu.runtime.executor import partition_buckets
    from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
    from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams
    from duplexumiconsensusreads_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(".bench_cache/xla_cache")
    cfg = SimConfig(
        n_molecules=60_000,
        read_len=150,
        n_positions=1250,
        mean_family_size=4,
        umi_error=0.01,
        duplex=True,
        seed=7,
    )
    batch, _ = simulate_batch(cfg)
    n_reads = int(np.asarray(batch.valid).sum())
    mesh = make_mesh(len(jax.devices()))

    variants = [
        ("full config5 (adj+cycle)", GroupingParams(strategy="adjacency", paired=True),
         ConsensusParams(mode="duplex", error_model="cycle", min_duplex_reads=1)),
        ("no error model (adj)", GroupingParams(strategy="adjacency", paired=True),
         ConsensusParams(mode="duplex", error_model=None, min_duplex_reads=1)),
        ("exact grouping + cycle", GroupingParams(strategy="exact", paired=True),
         ConsensusParams(mode="duplex", error_model="cycle", min_duplex_reads=1)),
        ("exact, no error model", GroupingParams(strategy="exact", paired=True),
         ConsensusParams(mode="duplex", error_model=None, min_duplex_reads=1)),
    ]
    n_dev = len(jax.devices())
    for name, gp, cp in variants:
        buckets = build_buckets(batch, capacity=2048, grouping=gp)
        part = partition_buckets(buckets, gp, cp)
        classes = [
            (cspec, shard_stacked(stack_buckets(cb, multiple_of=n_dev), mesh))
            for cb, cspec in part
        ]
        jax.block_until_ready([c[1] for c in classes])

        def run_all():
            return [presharded_pipeline(a, s, mesh) for s, a in classes]

        for o in run_all():
            np.asarray(o["n_families"])
        reps = 8
        t0 = time.monotonic()
        outs = [run_all() for _ in range(reps)]
        for ro in outs:
            for o in ro:
                np.asarray(o["n_families"])
        dt = (time.monotonic() - t0) / reps
        print(f"{name:28s} step={dt:.3f}s  {n_reads/dt/1e6:.3f}M reads/s")


if __name__ == "__main__":
    import os as _os

    sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    if len(sys.argv) > 1 and sys.argv[1] == "--report":
        if len(sys.argv) < 3:
            # a forgotten path must not fall through into the
            # multi-minute device-profiling run
            raise SystemExit("usage: profile_phases.py --report REPORT_JSON")
        raise SystemExit(report_busy_wall(sys.argv[2]))
    main()
