"""Device-ledger report for a streaming-executor trace capture.

Run: python tools/devstat.py trace.jsonl
       (per-bucket-class table — dispatches, buckets, executed FLOPs,
        device seconds, honest MFU, arithmetic intensity and the
        measured roofline verdict per class — plus the jit-compile
        ledger and the dev sum-check: record intervals must reproduce
        the summary's device_wait_fetch / dispatch phase totals —
        exit 1 on drift, the FLOP analogue of wirestat.py's byte
        sum-check)
     python tools/devstat.py trace.jsonl --json
       (the same analysis as one machine-readable JSON object)
     python tools/devstat.py trace.jsonl --peak-tflops 275
       (analyse a capture from a different machine; default is the
        shared table in telemetry/device.py keyed on the LOCAL device,
        DUT_PEAK_TFLOPS env override wins)

The analysis lives in duplexumiconsensusreads_tpu/telemetry/
devledger.py; this file is the CLI shell (same split as wirestat.py /
ledger.py).
"""

from __future__ import annotations

import argparse
import json
import sys

# cap the human table; --json is unabridged (class count is naturally
# small — capacity rungs x read lengths — but a sweep capture can grow)
_TABLE_ROWS = 40


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="devstat.py",
        description="per-class FLOP accounting / measured roofline for "
        "a `call --trace` capture",
    )
    ap.add_argument("trace", help="JSONL capture from call --trace")
    ap.add_argument(
        "--json", action="store_true",
        help="emit the analysis as one JSON object instead of text",
    )
    ap.add_argument(
        "--peak-tflops", type=float, default=None, metavar="T",
        help="peak TFLOP/s to score MFU against (default: the shared "
        "device table resolved for the local device; DUT_PEAK_TFLOPS "
        "env override wins over the table)",
    )
    args = ap.parse_args(argv)

    from duplexumiconsensusreads_tpu.telemetry import devledger, report
    from duplexumiconsensusreads_tpu.telemetry.device import device_peak_flops

    try:
        records = report.load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"devstat: {e}", file=sys.stderr)
        return 1
    problems = report.validate_trace(records)
    if problems:
        for p in problems:
            print(f"devstat: invalid capture: {p}", file=sys.stderr)
        return 1

    if args.peak_tflops is not None:
        peak, peak_entry = args.peak_tflops * 1e12, "cli"
    else:
        peak, peak_entry = device_peak_flops()

    classes = devledger.class_stats(records, peak_flops=peak)
    totals = devledger.device_totals(records, peak_flops=peak)
    roof = devledger.roofline(records, peak_flops=peak)
    compiles = devledger.compile_stats(records)
    rows, sum_ok = devledger.sum_check_dev(records)

    if args.json:
        print(json.dumps({
            "peak_flops": peak,
            "peak_entry": peak_entry,
            "classes": classes,
            "totals": totals,
            "roofline": roof,
            "compiles": compiles,
            "sum_check": {"ok": sum_ok, "rows": rows},
        }))
    else:
        if not totals:
            # legal (tracing predates the device ledger, or a zero-chunk
            # run) but worth saying out loud: every check is vacuous
            print("capture holds no dev records (pre-devledger capture?)")
        print(f"peak: {peak / 1e12:.0f} TFLOP/s ({peak_entry})")
        if roof:
            print(
                f"roofline: wire bw {roof['wire_bw_b_s'] / 1e6:.1f} MB/s  "
                f"ridge {roof['critical_intensity']} FLOP/B  "
                f"attainable frac {roof['attainable_frac']}"
            )
        if classes:
            print(
                f"{'class':>20} {'disp':>5} {'buckets':>8} {'GFLOP':>10} "
                f"{'dev_s':>8} {'mfu':>8} {'FLOP/B':>8}  verdict"
            )
            verdicts = (roof or {}).get("classes", {})
            for i, (key, d) in enumerate(classes.items()):
                if i >= _TABLE_ROWS:
                    print(f"  ... {len(classes) - _TABLE_ROWS} more classes "
                          f"(--json for all)")
                    break
                v = verdicts.get(key, {}).get("verdict", "-")
                print(
                    f"{key:>20} {d['n']:>5} {d['buckets']:>8} "
                    f"{d['flops'] / 1e9:>10.3f} {d['busy_s']:>8.3f} "
                    f"{d['mfu']:>8.2g} {d['intensity']:>8.1f}  {v}"
                )
        if totals:
            print(
                f"total: {totals['n']} dispatches  "
                f"{totals['flops'] / 1e9:.3f} GFLOP  "
                f"busy {totals['busy_s']:.3f}s  mfu {totals['mfu']:.2g}  "
                f"intensity {totals['intensity']:.1f} FLOP/B"
            )
        if compiles:
            print(
                f"jit compiles: {compiles['n_compiles']} "
                f"({compiles['compile_s']:.3f}s first-call wall)"
            )
            for key, d in compiles["per_class"].items():
                print(f"  {key}: n={d['n']} compile_s={d['compile_s']:.3f}")
        print()
        if rows:
            verdict = "OK" if sum_ok else "FAIL"
            print(f"dev sum-check (records vs phase totals): {verdict}")
            for r in rows:
                flag = "" if r["ok"] else "  <-- drift"
                print(
                    f"  {r['stage']}: records {r['records_s']}s vs "
                    f"summary {r['summary_s']}s{flag}"
                )
        else:
            print("dev sum-check skipped (no dev records)")

    if not sum_ok:
        print(
            "DEVICE LEDGER DRIFT: dev records disagree with the summary's "
            "phase totals — instrumentation bug or file corruption",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    import os as _os

    sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    raise SystemExit(main())
