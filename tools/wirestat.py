"""Byte-ledger report for a streaming-executor trace capture.

Run: python tools/wirestat.py trace.jsonl
       (per-chunk byte table, per-direction totals with packing /
        deflate ratios, measured bandwidth p50/p95/effective, the
        wire-floor decomposition, and the two byte sum-checks: ledger
        records vs the summary's running totals, and header/EOF
        overhead + shard wire bytes vs the finalised output's on-disk
        size — exit 1 on any drift, the byte analogue of
        trace_report.py's time sum-check)
     python tools/wirestat.py trace.jsonl --json
       (the same analysis as one machine-readable JSON object)
     python tools/wirestat.py trace.jsonl --out other.bam
       (check the on-disk size of a moved/renamed output instead of
        the path recorded in the capture)

The analysis lives in duplexumiconsensusreads_tpu/telemetry/ledger.py;
this file is the CLI shell (same split as trace_report.py/report.py).
"""

from __future__ import annotations

import argparse
import json
import sys

# cap the human table; a 200M-read run has hundreds of chunks and the
# totals/percentiles already carry the verdict (--json is unabridged)
_TABLE_ROWS = 40


def _fmt_bytes(n) -> str:
    return f"{n:,}" if isinstance(n, int) else "-"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="wirestat.py",
        description="per-chunk byte accounting / measured wire model "
        "for a `call --trace` capture",
    )
    ap.add_argument("trace", help="JSONL capture from call --trace")
    ap.add_argument(
        "--json", action="store_true",
        help="emit the analysis as one JSON object instead of text",
    )
    ap.add_argument(
        "--out", metavar="BAM", default=None,
        help="output BAM to size-check (default: the path recorded in "
        "the capture summary)",
    )
    args = ap.parse_args(argv)

    from duplexumiconsensusreads_tpu.telemetry import ledger, report

    try:
        records = report.load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"wirestat: {e}", file=sys.stderr)
        return 1
    problems = report.validate_trace(records)
    if problems:
        for p in problems:
            print(f"wirestat: invalid capture: {p}", file=sys.stderr)
        return 1

    # one record scan feeds every analysis below
    totals = ledger.byte_totals(records)
    rows, sum_ok = ledger.sum_check_bytes(records, totals=totals)
    disk_problems, disk_ok = ledger.output_check(
        records, out_path=args.out, totals=totals
    )
    fill = ledger.fill_stats(records)
    # the padding sum-check mirrors the byte one: fill rows recorded
    # per dispatch must reproduce the summary counters exactly
    fill_ok = fill.get("sum_check_ok", True)
    ok = sum_ok and disk_ok and fill_ok
    n_xfer = sum(t["n"] for t in totals.values())

    if args.json:
        print(json.dumps({
            "n_xfer_records": n_xfer,
            "totals": totals,
            "bandwidth": ledger.bandwidth_stats(records, totals=totals),
            "wire_floor": ledger.wire_floor(records, totals=totals),
            "packing": ledger.packing_stats(records, totals=totals),
            "chunks": ledger.per_chunk_bytes(records),
            "fill": fill,
            "overlap": ledger.overlap_stats(records),
            "devices": ledger.device_lanes(records),
            "summary_bytes": ledger.summary_bytes(records),
            "sum_check": {"ok": sum_ok, "rows": rows},
            "output_check": {"ok": disk_ok, "problems": disk_problems},
        }))
    else:
        if n_xfer == 0:
            # legal (tracing predates the ledger, or a zero-chunk run)
            # but worth saying out loud: every check below is vacuous
            print("capture holds no xfer records (pre-ledger capture?)")
        chunks = ledger.per_chunk_bytes(records)
        print(
            f"{'chunk':>6} {'h2d_logical':>12} {'h2d_wire':>12} "
            f"{'d2h_logical':>12} {'d2h_wire':>12} "
            f"{'shard_raw':>12} {'shard_wire':>12} {'fill':>6}  note"
        )
        for i, (chunk, row) in enumerate(chunks.items()):
            if i >= _TABLE_ROWS:
                print(f"  ... {len(chunks) - _TABLE_ROWS} more chunks "
                      f"(--json for all)")
                break
            h2d = row.get("h2d", {})
            d2h = row.get("d2h", {})
            shard = row.get("shard", {})
            note = "resumed" if shard.get("resumed") else ""
            # per-chunk bucket fill factor (the tuner's audit column);
            # "-" on pre-tuner captures and resume-reused chunks
            cfill = (
                f"{h2d['rows_real'] / h2d['rows_pad']:.2f}"
                if h2d.get("rows_pad") else "-"
            )
            print(
                f"{chunk:>6} {_fmt_bytes(h2d.get('logical', 0)):>12} "
                f"{_fmt_bytes(h2d.get('wire', 0)):>12} "
                f"{_fmt_bytes(d2h.get('logical', 0)):>12} "
                f"{_fmt_bytes(d2h.get('wire', 0)):>12} "
                f"{_fmt_bytes(shard.get('logical', 0)):>12} "
                f"{_fmt_bytes(shard.get('wire', 0)):>12} {cfill:>6}  {note}"
            )
        print()
        for direction in ledger.KNOWN_XFER_DIRS:
            t = totals.get(direction)
            if not t:
                continue
            extra = (
                f"  ({t['n_resumed']} resume-reused)" if t["n_resumed"] else ""
            )
            print(
                f"{direction:<6} n={t['n']:<5} logical={t['logical']:,} "
                f"wire={t['wire']:,} busy={t['busy_s']:.3f}s{extra}"
            )
        devs = ledger.device_lanes(records)
        if devs:
            # the mesh view: which device's share of the tunnel each
            # direction paid, and the mesh-alignment padding it shipped
            print(
                f"{'device':>8} {'h2d_wire':>12} {'d2h_wire':>12} "
                f"{'mesh_pad':>9}"
            )
            for lane, d in devs.items():
                print(
                    f"{lane:>8} {_fmt_bytes(d['h2d_wire']):>12} "
                    f"{_fmt_bytes(d['d2h_wire']):>12} {d['mesh_pad']:>9}"
                )
        if fill:
            verdict = "" if fill_ok else "  SUM-CHECK FAIL"
            mesh = (
                f" mesh_pad_buckets={fill['mesh_pad_buckets']:,}"
                if "mesh_pad_buckets" in fill else ""
            )
            print(
                f"fill: rows_real={fill['rows_real']:,} "
                f"rows_pad={fill['rows_pad']:,} "
                f"fill_factor={fill['fill_factor']}{mesh}{verdict}"
            )
        pack = ledger.packing_stats(records, totals=totals)
        if pack:
            print("packing: " + "  ".join(
                f"{k}={v}" for k, v in pack.items()
            ))
        bw = ledger.bandwidth_stats(records, totals=totals)
        for direction, b in bw.items():
            print(
                f"{direction} bandwidth: effective {b['effective_mb_s']} "
                f"MB/s  p50 {b['p50_mb_s']}  p95 {b['p95_mb_s']} "
                f"(per-transfer)"
            )
        fl = ledger.wire_floor(records, totals=totals)
        print(
            f"wire floor: h2d {fl['h2d_s']}s + d2h {fl['d2h_s']}s "
            f"(union {fl['floor_s']}s) over wall {fl['wall_s']}s "
            f"= frac {fl['frac']}"
        )
        ov = ledger.overlap_stats(records)
        if ov:
            # the ingest-overlap verdict: how much host-side chunk prep
            # the background producer hid behind device-facing work
            print(
                f"ingest overlap ({ov['mode']}): prep {ov['ingest_busy_s']}s "
                f"hidden {ov['overlap_s']}s = efficiency "
                f"{ov['efficiency']}  stall {ov['stall_s']}s  "
                f"backpressure {ov['backpressure_s']}s"
            )
        print()
        if rows:
            verdict = "OK" if sum_ok else "FAIL"
            print(f"byte sum-check (records vs summary totals): {verdict}")
            for r in rows:
                if not r["ok"]:
                    print(
                        f"  {r['key']}: records {r['records']:,} vs "
                        f"summary {r['summary']:,}"
                    )
        else:
            print("byte sum-check skipped (no summary: unclean shutdown)")
        if disk_ok:
            b = ledger.summary_bytes(records) or {}
            if "output_bytes" in b:
                print(
                    f"output check: OK (overhead + shard wire == "
                    f"{b['output_bytes']:,} bytes)"
                )
        else:
            print("output check: FAIL")
            for p in disk_problems:
                print(f"  {p}")

    if not ok:
        print(
            "BYTE LEDGER DRIFT: ledger records disagree with the summary "
            "totals or the on-disk output — instrumentation bug or file "
            "corruption",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    import os as _os

    sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    raise SystemExit(main())
