"""Fleet flight recorder CLI: stitch a fleet's service captures into
cross-daemon job timelines, aggregate fleet metrics, gate SLOs.

Run: python tools/fleet_report.py SPOOL
       (discovers every service*.trace.jsonl[.prev] on the spool plus
        queue.json and the per-daemon metrics/ snapshots, stitches the
        per-job admission→terminal timelines, prints the fleet report,
        and writes durable SPOOL/fleet_metrics.json — exit 1 on any
        structural violation or sum-check drift, the fleet analogue of
        trace_report.py's time check and wirestat.py's byte check)
     python tools/fleet_report.py CAPTURE [CAPTURE...]
       (capture-only mode: no journal/metrics cross-checks, no
        fleet_metrics.json write unless --out; run captures from
        per-job --trace may ride along for the Perfetto export)
     ... --json              one machine-readable JSON object
     ... --out PATH          fleet-metrics JSON destination ("-" skips)
     ... --prom PATH         Prometheus textfile exposition
     ... --chrome PATH       Perfetto export: one lane per daemon,
                             per-job colored slices (takeovers and
                             shard fan-out read as lane hops)
     ... --slo slo.toml --check-slo
                             evaluate declared SLO gates (p95 bounds,
                             deadline-hit-rate floors) — exit 1 on any
                             violated gate

The analysis lives in duplexumiconsensusreads_tpu/telemetry/fleet.py;
this file is the CLI shell (same split as trace_report.py/report.py,
wirestat.py/ledger.py, serve_report.py).
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _load_slo(path: str) -> dict:
    try:
        import tomllib
    except ModuleNotFoundError:  # stdlib tomllib is 3.11+
        try:
            import tomli as tomllib
        except ModuleNotFoundError:
            raise SystemExit(
                "fleet_report: reading --slo needs Python 3.11+ (stdlib "
                "tomllib) or the tomli package"
            )
    with open(path, "rb") as f:
        return tomllib.load(f)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="fleet_report.py",
        description="stitch N daemons' service captures into per-job "
        "cross-daemon timelines + fleet metrics + SLO gates",
    )
    ap.add_argument(
        "paths", nargs="+",
        help="a spool directory (captures/journal/metrics discovered) "
        "or explicit capture files (service and per-job run captures)",
    )
    ap.add_argument("--json", action="store_true",
                    help="one machine-readable JSON object")
    ap.add_argument(
        "--out", metavar="PATH", default=None,
        help="fleet-metrics JSON destination (default: "
        "SPOOL/fleet_metrics.json in spool mode, none in capture mode; "
        "'-' writes nowhere)",
    )
    ap.add_argument("--prom", metavar="PATH", default=None,
                    help="write a Prometheus textfile exposition here")
    ap.add_argument("--chrome", metavar="PATH", default=None,
                    help="write a Perfetto-openable fleet trace here")
    ap.add_argument("--slo", metavar="TOML", default=None,
                    help="declared SLO gates (see ARCHITECTURE.md "
                    "'Fleet observability' for the schema)")
    ap.add_argument(
        "--check-slo", action="store_true",
        help="evaluate --slo gates and exit 1 on any violation (the "
        "commit-time observability gate)",
    )
    args = ap.parse_args(argv)
    if args.check_slo and not args.slo:
        print("fleet_report: --check-slo needs --slo TOML", file=sys.stderr)
        return 2

    from duplexumiconsensusreads_tpu.telemetry import chrome, fleet

    spool = None
    capture_paths: list[str] = []
    for p in args.paths:
        if os.path.isdir(p):
            if spool is not None:
                print("fleet_report: at most one spool directory",
                      file=sys.stderr)
                return 2
            spool = p
            capture_paths += fleet.discover_service_captures(p)
        else:
            capture_paths.append(p)
    if not capture_paths:
        print(
            f"fleet_report: no service captures found"
            + (f" on spool {spool}" if spool else ""),
            file=sys.stderr,
        )
        return 1

    try:
        captures = fleet.load_captures(capture_paths)
    except (OSError, ValueError) as e:
        print(f"fleet_report: {e}", file=sys.stderr)
        return 1
    journal = (
        fleet.load_journal(os.path.join(spool, "queue.json"))
        if spool else None
    )
    metrics_docs = fleet.load_metrics_docs(spool) if spool else []

    stitched = fleet.stitch(captures, journal=journal)
    metrics = fleet.fleet_metrics(stitched, metrics_docs=metrics_docs)
    # ingest-overlap efficiency aggregated over any per-run captures
    # that rode along ({} when none carry ingest spans)
    overlap = fleet.run_overlap(captures.get("run", ()))
    # per-class MFU aggregated the same way ({} when none carry dev
    # records — pre-devledger captures)
    device = fleet.run_device(captures.get("run", ()))

    slo_rows = None
    slo_ok = True
    if args.slo:
        try:
            slo_rows, slo_ok = fleet.check_slo(metrics, _load_slo(args.slo))
        except (OSError, ValueError) as e:
            print(f"fleet_report: --slo: {e}", file=sys.stderr)
            return 2

    # durable fleet-metrics artifact: the scrape/gate surface beside
    # the journal (same tmp+fsync+rename protocol as every spool write)
    out_path = args.out
    if out_path is None and spool is not None:
        out_path = os.path.join(spool, "fleet_metrics.json")
    if out_path and out_path != "-":
        from duplexumiconsensusreads_tpu.io.durable import (
            unique_tmp,
            write_durable,
        )

        write_durable(
            out_path,
            json.dumps(metrics, sort_keys=True).encode(),
            tmp=unique_tmp(out_path),
        )
    if args.prom:
        with open(args.prom, "w") as f:
            f.write(fleet.render_prom(metrics))
    if args.chrome:
        doc = chrome.fleet_to_chrome(stitched, captures.get("run", ()))
        with open(args.chrome, "w") as f:
            json.dump(doc, f)

    if args.json:
        print(json.dumps({
            "jobs": stitched["jobs"],
            "metrics": metrics,
            "overlap": overlap,
            "device": device,
            "problems": stitched["problems"],
            "warnings": stitched["warnings"],
            "slo": slo_rows,
            "ok": stitched["ok"] and slo_ok,
        }, sort_keys=True))
    else:
        for line in fleet.render_report(stitched, metrics):
            print(line)
        if overlap:
            print()
            print(
                f"ingest overlap ({overlap['n_runs']} runs): prep "
                f"{overlap['ingest_busy_s']}s hidden "
                f"{overlap['overlap_s']}s = efficiency "
                f"{overlap['efficiency']}  stall {overlap['stall_s']}s  "
                f"backpressure {overlap['backpressure_s']}s"
            )
        if device:
            print()
            print(
                f"device ledger ({device['n_runs']} runs, peak "
                f"{device['peak_entry']}): {device['flops'] / 1e9:.3f} "
                f"GFLOP over {device['busy_s']:.3f}s busy = fleet mfu "
                f"{device['mfu']}"
            )
            for key, c in device["classes"].items():
                print(
                    f"  {key}: {c['flops'] / 1e9:.3f} GFLOP  "
                    f"busy {c['busy_s']:.3f}s  mfu {c['mfu']}"
                )
        if slo_rows is not None:
            print()
            for r in slo_rows:
                scope = f" class={r['class']}" if "class" in r else ""
                print(
                    f"slo {r['metric']}{scope}: {r['verdict'].upper()}"
                    + (f" (value {r['value']}, bound {r.get('bound')})"
                       if "value" in r else f" ({r.get('detail')})")
                )

    if not stitched["ok"]:
        print(
            "FLEET TIMELINE DRIFT: captures disagree with each other, "
            "the journal, or the admission→terminal sum-check — "
            "tampered/torn capture or instrumentation bug",
            file=sys.stderr,
        )
        return 1
    if args.check_slo and not slo_ok:
        print("SLO GATE FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    import os as _os

    sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    raise SystemExit(main())
