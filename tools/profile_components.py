"""Component-share profile of the fused pipeline on the real chip.

Times the bench compute workload under ablations so the round-4 perf
work attacks the right term:
  full        — grouping + ssc + error-model(2nd ssc) + duplex (bench path)
  no_errmodel — error_model="none": removes pass-1 ssc + fit + capped re-ssc
  ssc_only    — ssc + duplex on precomputed family ids (grouping ablated)
  group_only  — group_kernel alone (closure + table, no consensus)

Run: python tools/profile_components.py  (defaults to the real chip;
DUT_PROF_READS / DUT_PROF_REPS to resize).
"""

from __future__ import annotations

import dataclasses
import os
import time

import numpy as np


def main() -> None:
    import jax

    from duplexumiconsensusreads_tpu.bucketing import build_buckets, stack_buckets
    from duplexumiconsensusreads_tpu.parallel import make_mesh
    from duplexumiconsensusreads_tpu.parallel.sharded import (
        presharded_pipeline,
        shard_stacked,
    )
    from duplexumiconsensusreads_tpu.runtime.executor import partition_buckets
    from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
    from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams
    from duplexumiconsensusreads_tpu.utils.compile_cache import enable_compile_cache

    enable_compile_cache(
        os.path.join(os.environ.get("DUT_BENCH_CACHE", ".bench_cache"), "xla_cache")
    )

    n_target = int(os.environ.get("DUT_PROF_READS", 600_000))
    capacity = int(os.environ.get("DUT_PROF_CAPACITY", 2048))
    reps = int(os.environ.get("DUT_PROF_REPS", 10))

    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex", error_model="cycle", min_duplex_reads=1)

    n_mol = max(64, n_target // 9)
    batch, _ = simulate_batch(
        SimConfig(
            n_molecules=n_mol,
            read_len=150,
            n_positions=max(8, n_mol // 48),
            mean_family_size=4,
            umi_error=0.01,
            duplex=True,
            seed=7,
        )
    )
    n_reads = int(np.asarray(batch.valid).sum())
    buckets = build_buckets(batch, capacity=capacity, grouping=gp)
    mesh = make_mesh(len(jax.devices()))

    part = partition_buckets(buckets, gp, cp, "matmul")
    classes = []
    for cbuckets, cspec in part:
        stacked = stack_buckets(cbuckets, multiple_of=len(jax.devices()))
        classes.append((cbuckets, cspec, shard_stacked(stacked, mesh)))
    jax.block_until_ready([c[2] for c in classes])
    for cbuckets, cspec, args in classes:
        print(
            f"# class: n_buckets={args['pos'].shape[0]} capacity={cbuckets[0].capacity}"
            f" u_max={cspec.u_max} f_max={cspec.f_max} grouping={cspec.grouping.strategy}"
        )

    def timed(label, fn):
        for o in fn():
            np.asarray(o["n_families"])  # compile + barrier
        t0 = time.monotonic()
        outs = [fn() for _ in range(reps)]
        np.asarray(outs[-1][-1]["n_families"])
        dt = (time.monotonic() - t0) / reps
        print(f"{label:14s} {dt*1e3:8.1f} ms  {n_reads/dt/1e6:6.3f} M reads/s")
        return dt

    t_full = timed(
        "full",
        lambda: [presharded_pipeline(args, cspec, mesh) for _, cspec, args in classes],
    )

    # error model off: removes the fit pass + capped re-ssc
    t_noem = timed(
        "no_errmodel",
        lambda: [
            presharded_pipeline(
                args,
                dataclasses.replace(
                    cspec,
                    consensus=dataclasses.replace(cspec.consensus, error_model="none"),
                ),
                mesh,
            )
            for _, cspec, args in classes
        ],
    )

    # grouping ablated: exact strategy (no Hamming GEMM, no closure,
    # no table lexsort) — NOT semantically equivalent, purely a timer
    t_exact = timed(
        "exact_group",
        lambda: [
            presharded_pipeline(
                args,
                dataclasses.replace(
                    cspec,
                    grouping=dataclasses.replace(cspec.grouping, strategy="exact"),
                ),
                mesh,
            )
            for _, cspec, args in classes
        ],
    )

    # single-strand mode: duplex merge ablated
    t_ss = timed(
        "ss_mode",
        lambda: [
            presharded_pipeline(
                args,
                dataclasses.replace(
                    cspec,
                    consensus=dataclasses.replace(cspec.consensus, mode="single_strand"),
                ),
                mesh,
            )
            for _, cspec, args in classes
        ],
    )

    print(
        f"# shares vs full: errmodel_2nd_pass={100*(t_full-t_noem)/t_full:.1f}% "
        f"adjacency_machinery={100*(t_full-t_exact)/t_full:.1f}% "
        f"duplex_merge={100*(t_full-t_ss)/t_full:.1f}%"
    )


if __name__ == "__main__":
    main()
