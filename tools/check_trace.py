"""Schema validator for telemetry captures (CI gate).

Run: python tools/check_trace.py trace.jsonl [--require-summary]

Exit 0 when the capture conforms to the telemetry contract
(telemetry/trace.py: meta header first, known span stages and event
names, byte-ledger xfer records with registered directions and
integer byte counts, numeric non-negative timestamps, one terminal
summary whose n_events matches the record count and whose byte totals
are integers); exit 1 listing every violation otherwise. ``--require-summary`` additionally fails a capture that
lacks the terminal summary record — i.e. one from a run that did not
shut down cleanly — which is what the tier-1 test uses: a synthetic
run's capture must always be COMPLETE, not merely well-formed.

The capture KIND is read from the meta header: a ``run`` capture (the
streaming executor's per-chunk spans, the default) gets the core
checks; a ``service`` capture (a ``dut-serve`` daemon's job-lifecycle
record) additionally must keep every job event — including the fleet
events ``job_shed``, ``lease_takeover`` and ``job_fenced`` — on its
job-scoped ``job-<id>`` lane and every service heartbeat carrying the
queue snapshot — the contract ``tools/serve_report.py`` decomposes.

The rules live in telemetry/report.py (validate_trace /
validate_service_trace) so the CLI, the tier-1 tests, and the report
tools all enforce the same contract.
"""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="check_trace.py",
        description="validate a `call --trace` capture against the "
        "telemetry schema",
    )
    ap.add_argument("trace", help="JSONL capture from call --trace")
    ap.add_argument(
        "--require-summary", action="store_true",
        help="also fail captures without the terminal summary record "
        "(runs that did not shut down cleanly)",
    )
    args = ap.parse_args(argv)

    from duplexumiconsensusreads_tpu.telemetry import report

    try:
        records = report.load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"check_trace: {e}", file=sys.stderr)
        return 1
    kind = report.capture_kind(records)
    if kind == "service":
        problems = report.validate_service_trace(records)
    else:
        problems = report.validate_trace(records)
    if args.require_summary and report.summary_record(records) is None:
        problems.append("no terminal summary record (unclean shutdown?)")
    if problems:
        for p in problems:
            print(f"check_trace: {args.trace}: {p}", file=sys.stderr)
        return 1
    n_spans = sum(1 for r in records if r.get("type") == "span")
    n_events = sum(1 for r in records if r.get("type") == "event")
    n_xfer = sum(1 for r in records if r.get("type") == "xfer")
    n_dev = sum(1 for r in records if r.get("type") == "dev")
    print(
        f"[check_trace] {args.trace}: OK "
        f"({kind} capture, {n_spans} spans, {n_events} events, "
        f"{n_xfer} xfer, {n_dev} dev)",
        file=sys.stderr,
    )
    return 0


if __name__ == "__main__":
    import os as _os

    sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    raise SystemExit(main())
