"""Analyse a streaming-executor trace capture (`call --trace`).

Run: python tools/trace_report.py trace.jsonl
       (human report: per-lane utilization, per-stage p50/p95/max,
        the per-chunk critical path, and the sum-check of span totals
        against the embedded RunReport.seconds busy totals — exit 1
        when the capture and the report disagree, the telemetry twin
        of profile_phases.py's busy>wall canary)
     python tools/trace_report.py trace.jsonl --json
       (the same analysis as one machine-readable JSON object)
     python tools/trace_report.py trace.jsonl --chrome out.json
       (also export Chrome trace events; open out.json in
        https://ui.perfetto.dev to see every lane as a track)

The analysis lives in duplexumiconsensusreads_tpu/telemetry/report.py;
this file is the CLI shell.
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="trace_report.py",
        description="critical path / utilization / percentile report "
        "for a `call --trace` capture",
    )
    ap.add_argument("trace", help="JSONL capture from call --trace")
    ap.add_argument(
        "--json", action="store_true",
        help="emit the analysis as one JSON object instead of text",
    )
    ap.add_argument(
        "--chrome", metavar="OUT_JSON",
        help="also export the capture as Chrome trace events (Perfetto)",
    )
    args = ap.parse_args(argv)

    from duplexumiconsensusreads_tpu.telemetry import chrome, report

    try:
        records = report.load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"trace_report: {e}", file=sys.stderr)
        return 1
    problems = report.validate_trace(records)
    if problems:
        for p in problems:
            print(f"trace_report: invalid capture: {p}", file=sys.stderr)
        return 1

    if args.chrome:
        n = chrome.write_chrome(records, args.chrome)
        print(f"[trace_report] wrote {n} Chrome trace events → {args.chrome}",
              file=sys.stderr)

    if args.json:
        # same guard as the text path: a summary-less capture (crashed
        # run — legal post-mortem evidence) has nothing to sum-check
        # against and must not exit 1 as if instrumentation rotted
        s = report.summary_record(records)
        if s is not None and s.get("seconds"):
            rows, ok = report.sum_check(records)
            sum_out = {"ok": ok, "rows": rows}
        else:
            ok = True
            sum_out = {"ok": True, "rows": [],
                       "skipped": "no summary record (unclean shutdown)"}
        out = {
            "wall_s": report.wall_seconds(records),
            "lanes": report.lane_utilization(records),
            "stages": report.stage_stats(records),
            "chunks": report.chunk_latency_percentiles(records),
            "sum_check": sum_out,
        }
        print(json.dumps(out))
        return 0 if ok else 1

    lines, ok = report.render_report(records)
    for ln in lines:
        print(ln)
    if not ok:
        print(
            "TRACE/REPORT MISMATCH: per-stage span totals disagree with "
            "RunReport.seconds — instrumentation bug",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    import os as _os

    sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    raise SystemExit(main())
