#!/usr/bin/env python
"""Invariant linter runner — see duplexumiconsensusreads_tpu/analysis/.

    python tools/dutlint.py              # lint package + tools + anchors
    python tools/dutlint.py --list-rules
    python tools/dutlint.py --rule fault-registry -v
    python tools/dutlint.py --json       # machine-readable (CI)

Exit 1 on any non-allowlisted finding. Sibling of tools/check_trace.py
(runtime capture validation) — this one validates the SOURCE against
the same contracts, at PR time instead of run time.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from duplexumiconsensusreads_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
