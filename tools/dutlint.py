#!/usr/bin/env python
"""Invariant linter runner — see duplexumiconsensusreads_tpu/analysis/.

    python tools/dutlint.py              # lint package + tools + anchors
    python tools/dutlint.py --list-rules
    python tools/dutlint.py --rule state-machine -v   # bisect one pass
    python tools/dutlint.py --json       # machine-readable (CI/editors)
    python tools/dutlint.py --strict     # + stale allowlist = exit 1

Exit 1 on any non-allowlisted finding (and, under --strict, on stale
allowlist entries). Sibling of tools/check_trace.py (runtime capture
validation) — this one validates the SOURCE against the same
contracts, at PR time instead of run time; tools/ci_check.sh runs
both as the one-command commit gate.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from duplexumiconsensusreads_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())
