"""Bench trajectory report + regression gate over BENCH_r0N.json.

Run: python tools/bench_history.py
       (default: every BENCH_r[0-9]*.json beside the repo root, in
        round order — prints the canonical-metric trajectory table
        with the last-round delta; rounds whose driver parse failed,
        like r5's truncated tail, are salvaged per-key)
     python tools/bench_history.py --check [--threshold 0.5]
       (the GATE: exit 1 when a gate metric's latest reading regresses
        beyond the threshold against the previous round that measured
        it — wired into the bench leg so a regression fails the run
        visibly instead of landing silently in the diary)
     python tools/bench_history.py --candidate fresh.json
       (append a bench RESULT json — e.g. the bench's own
        <cache>/bench_full.json — as the newest round; with --check
        this gates a fresh run against the recorded trajectory)
     python tools/bench_history.py --json
       (the trajectory + gate verdict as one JSON object)

The analysis lives in duplexumiconsensusreads_tpu/benchhist.py; this
file is the CLI shell (same split as trace_report.py/report.py).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="bench_history.py",
        description="canonical bench-metric trajectory over the "
        "driver's BENCH_r0N.json captures, with a regression gate",
    )
    ap.add_argument(
        "paths", nargs="*",
        help="BENCH_r0N.json files in round order (default: "
        "BENCH_r[0-9]*.json in --dir)",
    )
    ap.add_argument("--dir", default=".", help="where to glob the "
                    "default trajectory files (default: cwd)")
    ap.add_argument(
        "--candidate", metavar="JSON", default=None,
        help="a bench result JSON to append as the newest round",
    )
    ap.add_argument("--json", action="store_true", help="machine-readable")
    ap.add_argument(
        "--check", action="store_true",
        help="exit 1 when a gate metric regressed beyond --threshold",
    )
    ap.add_argument(
        "--threshold", type=float, default=0.5,
        help="fractional regression bound for --check (default 0.5; "
        "loose on purpose — the tunnel wire varies ~3x intra-day)",
    )
    ap.add_argument(
        "--metric", action="append", dest="metrics", metavar="KEY",
        help="gate this metric instead of the defaults (repeatable)",
    )
    args = ap.parse_args(argv)

    from duplexumiconsensusreads_tpu import benchhist

    paths = args.paths or benchhist.default_paths(args.dir)
    if args.candidate:
        paths = list(paths) + [args.candidate]
    if not paths:
        print("bench_history: no BENCH_r0N.json files found", file=sys.stderr)
        return 2
    rounds = []
    for p in paths:
        try:
            rounds.append(benchhist.load_round(p))
        except (OSError, ValueError) as e:
            print(f"bench_history: {p}: {e}", file=sys.stderr)
            return 2

    ok, problems = benchhist.check_regression(
        rounds, threshold=args.threshold, metrics=args.metrics
    )
    if args.json:
        print(json.dumps({
            "trajectory": benchhist.trajectory(rounds),
            "salvaged": [r["name"] for r in rounds if r["salvaged"]],
            "gate": {
                "checked": bool(args.check), "ok": ok,
                "threshold": args.threshold, "problems": problems,
            },
        }))
    else:
        for line in benchhist.render_table(rounds):
            print(line)
        if args.check:
            if ok:
                print(
                    f"gate: OK (no gate metric regressed more than "
                    f"{args.threshold * 100:.0f}% vs its previous reading)"
                )
            else:
                print("gate: FAIL")
                for p in problems:
                    print(f"  {p}")
    if args.check and not ok:
        print(
            "BENCH REGRESSION: canonical metrics fell beyond the "
            "threshold — see the trajectory above",
            file=sys.stderr,
        )
        return 1
    return 0


if __name__ == "__main__":
    import os as _os

    sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    raise SystemExit(main())
