"""Micro-tuner for the ssc reduction method (VERDICT r2 item 4).

Times the FUSED pipeline (the only honest scope: isolated-kernel
rankings invert in-pipeline, as the r2 pallas journal showed) on one
representative dispatch-class geometry, sweeping ssc_method and the
blockseg tile height. Run on the real chip:

    python tools/tune_ssc.py

Journal (v5e-1, axon tunnel, 2026-07-30):

Full bench.py geometry (capacity=2048, duplex+adjacency+cycle error
model, 527k reads, 283 buckets — the honest in-pipeline scope):
  matmul    2.386M reads/s  step 0.221s  err 6.9e-5  <-- TPU default
  blockseg  1.701M reads/s  step 0.310s  err 6.9e-5  (T=128, exact)
  runsum    1.426M reads/s  step 0.370s  err 3.3e-4  REJECTED: the
            prefix-cancellation noise is not just a qual wobble — it
            multiplies the measured consensus error rate 4.8x.
So the VERDICT-r2 hypothesis ("skip the one-hot padding FLOPs and MFU
rises") is REFUTED with numbers, like the pallas kernel before it:
blockseg cuts ssc FLOPs 16x (2R*129*C vs 2R*2049*C) yet loses 1.4x in
wall — the dense GEMM's padding FLOPs ride idle MXU capacity while
blockseg's argsort + row-gather + (T+1)-row scatter are real HBM/VPU
work on the critical path. MFU accounting confirms: matmul shows
11.55 analytic TFLOP/s (mfu 0.059) vs blockseg 3.14 (mfu 0.016) in
nearly the same wall time — the "wasted" FLOPs were nearly free.

This tuner's smaller workload (~190k reads, ~95 buckets) is dispatch-
latency-dominated on a tunneled chip — every method lands within 10%
(matmul 0.841M, blockseg T=64 0.883M / T=128 0.877M / T=256 0.843M /
T=512 0.773M, runsum 0.870M, segment 0.858M reads/s) — which is why
method decisions are made on the full bench, not this sweep.

On XLA-CPU the ranking INVERTS: blockseg 74.6k reads/s vs matmul
17.8k (4.2x) — the padding FLOPs are real work on a scalar core.
blockseg is therefore the CPU-backend default
(runtime/executor.py DEFAULT_SSC_METHOD_CPU).

r4 precision refutation (standalone GEMM micro at the exact bench
shapes, (280, 2048, 1025)x(280, 2048, 751), true device->host sync
barrier): f32 default 30.6ms, bf16-cast inputs 37.1ms (SLOWER — the
casts materialize ~2.3GB of copies the fused f32 path never writes),
hi/lo split-bf16 52.1ms, precision=HIGHEST 50.8ms. So "run the
evidence GEMM in bf16 for speed" is REFUTED at these shapes before
even reaching the parity question (bf16 loglik sums would also risk
argmax near-tie flips vs the f64 oracle). The r4 wins came from
COLUMN structure instead: the fit-only pass drops the depth block
(20% fewer columns, exact via the loglik-sign mask) — columns must be
dropped BEFORE the dot, XLA cannot narrow a GEMM through output
slices.

r5 fit-gather refutation (the error-model fit's (R, L) consensus
row-gather, ~30.4 ms standalone at bench shapes, looked like the next
structural target). Three alternatives, all measured on v5e:
  one-hot GEMM gather   33.1 ms standalone (A (R,F) bf16 materializes
                        ~4 MB/bucket of one-hot the take never needs)
  family-side counts    pass1+fit standalone 84.2 vs gather's 87.1 ms
  (fit_impl="counts",   — but IN-PIPELINE (the only honest scope) it
  +4L GEMM columns,     LOSES: full step 170.0 vs 164.4 ms (2x each,
  tally via strided     interleaved). The fused pipeline CSEs the
  slices)               one-hot family matrix across passes and fuses
                        the gather into the fit's reductions, so the
                        gather's in-situ cost is far below standalone
                        while the +4L column widening is real MXU work
                        either way. Kept selectable as
                        PipelineSpec.fit_impl / DUT_FIT_IMPL with a
                        bit-parity test (test_fit_from_counts_*).
  memory footnote: the counts must stay in flat (F, 4L) GEMM layout —
  reshaping to (F, L, 4) puts 4 lanes on the minor axis and TPU
  T(8,128) tiling pads it 32x (measured 22.3 GB alloc, OOM).
So the error model's remaining ~30% share is structurally floored for
exact oracle parity: pass 1 must reduce ALL evidence (a 4L+1-column
GEMM, the same work as the final pass), the fit must visit the
(R, L) grid once in some form (gather, one-hot, or counts — all
measured), and bf16 was refuted r4. The ~50 ms block is two
irreducible GEMM-scale passes, not an unoptimized kernel.

r5 config4 (jumbo/exome, capacity 4096, dominant class R=4096
u_max=2048 f_max=4096 x49 buckets) investigation — BENCH_r04 recorded
it at 2.32M reads/s (step 86.5 ms), 40% behind config3. Method sweep
at the exact config4 geometry, warm, same process:
  matmul   72.2 ms  2.773M reads/s   <-- still the winner
  segment  80.4 ms  2.493M
  blockseg 86.0-86.6 ms (T=128/256/512), 2.31-2.33M
Adjacency ablation at u_max=2048: exact-grouping saves only ~3 ms
(68.7 -> 65.9 ms in the cleanest round) — the (U, U) grid is NOT the
cost. The 86.5 ms canonical reading reproduces only in a process's
FIRST timing burst right after fresh compiles (one run measured
85.8 ms then 72.2 ms on re-run); steady-state is 68-72 ms => ~2.8-2.9M
reads/s. Fix shipped: run_per_config times two rounds and reports the
best (the CPU-denominator discipline). The remaining gap to config3 is
the jumbo geometry's honest price: per-read one-hot GEMM work scales
with f_max, and f_max doubles (4096 vs 2048 per same 2x reads).
"""

from __future__ import annotations

import time

import numpy as np


def main() -> None:
    import jax

    from duplexumiconsensusreads_tpu.bucketing import build_buckets, stack_buckets
    from duplexumiconsensusreads_tpu.parallel import make_mesh
    from duplexumiconsensusreads_tpu.parallel.sharded import (
        presharded_pipeline,
        shard_stacked,
    )
    from duplexumiconsensusreads_tpu.runtime.executor import partition_buckets
    from duplexumiconsensusreads_tpu.simulate import SimConfig, simulate_batch
    from duplexumiconsensusreads_tpu.types import ConsensusParams, GroupingParams

    gp = GroupingParams(strategy="adjacency", paired=True)
    cp = ConsensusParams(mode="duplex", error_model="cycle", min_duplex_reads=1)
    cfg = SimConfig(
        n_molecules=22_000,
        read_len=150,
        n_positions=460,
        mean_family_size=4,
        umi_error=0.01,
        duplex=True,
        seed=7,
    )
    batch, _ = simulate_batch(cfg)
    n_reads = int(np.asarray(batch.valid).sum())
    buckets = build_buckets(batch, capacity=2048, grouping=gp)
    mesh = make_mesh(len(jax.devices()))

    plans = [("matmul", None)] + [
        ("blockseg", t) for t in (64, 128, 256, 512)
    ] + [("runsum", None), ("segment", None)]
    import dataclasses as _dc

    for method, t in plans:
        jax.clear_caches()
        part = partition_buckets(buckets, gp, cp, method)
        classes = [
            (
                cspec if t is None else _dc.replace(cspec, blockseg_t=t),
                shard_stacked(stack_buckets(cb, multiple_of=1), mesh),
            )
            for cb, cspec in part
        ]
        jax.block_until_ready([c[1] for c in classes])

        def run_all():
            return [presharded_pipeline(args, cspec, mesh) for cspec, args in classes]

        for o in run_all():
            np.asarray(o["n_families"])  # compile + sync
        reps = 6
        t0 = time.monotonic()
        outs = [run_all() for _ in range(reps)]
        for rep_outs in outs:
            for o in rep_outs:
                np.asarray(o["n_families"])
        dt = (time.monotonic() - t0) / reps
        label = method if t is None else f"{method}(T={t})"
        print(f"{label:16s} step={dt:.3f}s  {n_reads/dt/1e6:.3f}M reads/s")


if __name__ == "__main__":
    main()
