"""Micro-tuner for the ssc reduction method (VERDICT r2 item 4).

Times the FUSED pipeline (the only honest scope: isolated-kernel
rankings invert in-pipeline, as the r2 pallas journal showed) on one
representative dispatch-class geometry, sweeping ssc_method and the
blockseg tile height. Run on the real chip:

    python tools/tune_ssc.py

Journal (v5e-1, axon tunnel, 2026-07-30):

Full bench.py geometry (capacity=2048, duplex+adjacency+cycle error
model, 527k reads, 283 buckets — the honest in-pipeline scope):
  matmul    2.386M reads/s  step 0.221s  err 6.9e-5  <-- TPU default
  blockseg  1.701M reads/s  step 0.310s  err 6.9e-5  (T=128, exact)
  runsum    1.426M reads/s  step 0.370s  err 3.3e-4  REJECTED: the
            prefix-cancellation noise is not just a qual wobble — it
            multiplies the measured consensus error rate 4.8x.
So the VERDICT-r2 hypothesis ("skip the one-hot padding FLOPs and MFU
rises") is REFUTED with numbers, like the pallas kernel before it:
blockseg cuts ssc FLOPs 16x (2R*129*C vs 2R*2049*C) yet loses 1.4x in
wall — the dense GEMM's padding FLOPs ride idle MXU capacity while
blockseg's argsort + row-gather + (T+1)-row scatter are real HBM/VPU
work on the critical path. MFU accounting confirms: matmul shows
11.55 analytic TFLOP/s (mfu 0.059) vs blockseg 3.14 (mfu 0.016) in
nearly the same wall time — the "wasted" FLOPs were nearly free.

This tuner's smaller workload (~190k reads, ~95 buckets) is dispatch-
latency-dominated on a tunneled chip — every method lands within 10%
(matmul 0.841M, blockseg T=64 0.883M / T=128 0.877M / T=256 0.843M /
T=512 0.773M, runsum 0.870M, segment 0.858M reads/s) — which is why
method decisions are made on the full bench, not this sweep.

On XLA-CPU the ranking INVERTS: blockseg 74.6k reads/s vs matmul
17.8k (4.2x) — the padding FLOPs are real work on a scalar core.
blockseg is therefore the CPU-backend default
(runtime/executor.py DEFAULT_SSC_METHOD_CPU).

r4 precision refutation (standalone GEMM micro at the exact bench
shapes, (280, 2048, 1025)x(280, 2048, 751), true device->host sync
barrier): f32 default 30.6ms, bf16-cast inputs 37.1ms (SLOWER — the
casts materialize ~2.3GB of copies the fused f32 path never writes),
hi/lo split-bf16 52.1ms, precision=HIGHEST 50.8ms. So "run the
evidence GEMM in bf16 for speed" is REFUTED at these shapes before
even reaching the parity question (bf16 loglik sums would also risk
argmax near-tie flips vs the f64 oracle). The r4 wins came from
COLUMN structure instead: the fit-only pass drops the depth block
(20% fewer columns, exact via the loglik-sign mask) — columns must be
dropped BEFORE the dot, XLA cannot narrow a GEMM through output
slices.

r5 fit-gather refutation (the error-model fit's (R, L) consensus
row-gather, ~30.4 ms standalone at bench shapes, looked like the next
structural target). Three alternatives, all measured on v5e:
  one-hot GEMM gather   33.1 ms standalone (A (R,F) bf16 materializes
                        ~4 MB/bucket of one-hot the take never needs)
  family-side counts    pass1+fit standalone 84.2 vs gather's 87.1 ms
  (fit_impl="counts",   — but IN-PIPELINE (the only honest scope) it
  +4L GEMM columns,     LOSES: full step 170.0 vs 164.4 ms (2x each,
  tally via strided     interleaved). The fused pipeline CSEs the
  slices)               one-hot family matrix across passes and fuses
                        the gather into the fit's reductions, so the
                        gather's in-situ cost is far below standalone
                        while the +4L column widening is real MXU work
                        either way. Kept selectable as
                        PipelineSpec.fit_impl / DUT_FIT_IMPL with a
                        bit-parity test (test_fit_from_counts_*).
  memory footnote: the counts must stay in flat (F, 4L) GEMM layout —
  reshaping to (F, L, 4) puts 4 lanes on the minor axis and TPU
  T(8,128) tiling pads it 32x (measured 22.3 GB alloc, OOM).
So the error model's remaining ~30% share is structurally floored for
exact oracle parity: pass 1 must reduce ALL evidence (a 4L+1-column
GEMM, the same work as the final pass), the fit must visit the
(R, L) grid once in some form (gather, one-hot, or counts — all
measured), and bf16 was refuted r4. The ~50 ms block is two
irreducible GEMM-scale passes, not an unoptimized kernel.

r5 config4 (jumbo/exome, capacity 4096, dominant class R=4096
u_max=2048 f_max=4096 x49 buckets) investigation — BENCH_r04 recorded
it at 2.32M reads/s (step 86.5 ms), 40% behind config3. Method sweep
at the exact config4 geometry, warm, same process:
  matmul   72.2 ms  2.773M reads/s   <-- still the winner
  segment  80.4 ms  2.493M
  blockseg 86.0-86.6 ms (T=128/256/512), 2.31-2.33M
Adjacency ablation at u_max=2048: exact-grouping saves only ~3 ms
(68.7 -> 65.9 ms in the cleanest round) — the (U, U) grid is NOT the
cost. The 86.5 ms canonical reading reproduces only in a process's
FIRST timing burst right after fresh compiles (one run measured
85.8 ms then 72.2 ms on re-run); steady-state is 68-72 ms => ~2.8-2.9M
reads/s. Fix shipped: run_per_config times two rounds and reports the
best (the CPU-denominator discipline). The remaining gap to config3 is
the jumbo geometry's honest price: per-read one-hot GEMM work scales
with f_max, and f_max doubles (4096 vs 2048 per same 2x reads).

v2 (PR 13): the race body moved to tuning.race_ssc_methods and this
tool became the offline DRIVER: it races whatever kernels are LIVE —
the journal numbers above predate the r5 min-rank propagation rewrite,
which changed the grouping FLOP mix, so the method table needed
re-racing — and records the per-method table plus the WINNER in a JSON
result (last stdout line; --json writes it to a file) instead of only
a human table. Re-run on hardware after any kernel rewrite; the
executors' DEFAULT_SSC_METHOD* constants cite this tool's journal.
"""

from __future__ import annotations

import argparse
import json
import sys


def build_result(race: dict) -> dict:
    """The tool's JSON contract around a tuning.race_ssc_methods result:
    the per-method table verbatim plus the winner, stamped with the
    tool's schema version so downstream consumers (the serve layer's
    verdict store, a future bench leg) can trust the shape. Pure
    function — unit-testable without a device race."""
    return {
        "tool": "tune_ssc",
        "version": 2,
        "backend": race["backend"],
        "n_reads": race["n_reads"],
        "capacity": race["capacity"],
        "reps": race["reps"],
        "methods": race["methods"],
        # the re-raced table's verdict: the method the executors should
        # default to on THIS backend for this FLOP mix (the table above
        # was stale since the r5 min-rank propagation rewrite — this
        # race always measures the live kernels)
        "winner": race["winner"],
        "winner_method": race["winner_method"],
    }


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tune_ssc.py",
        description="offline ssc-method race (fused pipeline, live "
        "kernels) — prints a table and a final JSON line with the "
        "winner; the journal in this file's docstring records past "
        "hardware rounds",
    )
    ap.add_argument(
        "--json", metavar="PATH", default=None,
        help="also write the result JSON here (stdout always carries "
        "it as the LAST line, the bench stdout contract)",
    )
    ap.add_argument(
        "--reps", type=int, default=6,
        help="timed repetitions per method (default 6)",
    )
    ap.add_argument(
        "--molecules", type=int, default=22_000,
        help="simulated molecules for the race workload (default 22000)",
    )
    ap.add_argument(
        "--capacity", type=int, default=2048,
        help="bucket capacity of the race geometry (default 2048)",
    )
    ap.add_argument(
        "--methods", default="matmul,blockseg,runsum,segment",
        help="comma-separated ssc methods to race",
    )
    ap.add_argument(
        "--blockseg-t", default="64,128,256,512",
        help="blockseg tile heights to sweep (comma-separated)",
    )
    args = ap.parse_args(argv)

    from duplexumiconsensusreads_tpu.tuning import race_ssc_methods

    race = race_ssc_methods(
        methods=tuple(m for m in args.methods.split(",") if m),
        blockseg_ts=tuple(
            int(t) for t in args.blockseg_t.split(",") if t
        ),
        reps=args.reps,
        n_molecules=args.molecules,
        capacity=args.capacity,
    )
    for label, row in race["methods"].items():
        print(
            f"{label:16s} step={row['step_s']:.3f}s  "
            f"{row['reads_per_sec'] / 1e6:.3f}M reads/s",
            file=sys.stderr,
        )
    print(
        f"winner: {race['winner']} ({race['backend']} backend)",
        file=sys.stderr,
    )
    result = build_result(race)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(result, f)
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    import os as _os

    sys.path.insert(
        0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__)))
    )
    raise SystemExit(main())
