#!/bin/sh
# CI gate: the repo's commit-time contracts, runnable as one command.
#
#   sh tools/ci_check.sh
#
# Five legs, all exit-1 on violation:
#
#   1. dutlint --strict over the whole default set (package + tools/ +
#      test anchors): every invariant rule active, zero non-allowlisted
#      findings, AND zero stale allowlist entries — a suppression whose
#      finding was fixed must be pruned in the same change. The JSON
#      report is archived to bench_logs/dutlint.json, and the active
#      rule count must match README.md's documented rule table
#      (between the dutlint-rule-table markers) — adding a rule
#      without documenting it is itself a gate failure.
#   2. check_trace --require-summary over the committed fixture capture
#      (tests/data/run.fixture.trace.jsonl): the telemetry schema
#      validator itself must accept a known-good, COMPLETE capture —
#      so a schema change that would reject healthy runs (or a
#      validator regression that accepts torn ones) fails here, not in
#      production triage.
#   3. fleet_report over the committed 2-daemon fixture captures
#      (tests/data/fleet.fixture.{a,b}.trace.jsonl — a SIGKILL
#      takeover + a sharded parent): the cross-daemon stitcher must
#      reconstruct every timeline with the admission→terminal
#      sum-check green, so a stitching/schema regression fails at
#      commit time, not when a production fleet needs post-morteming.
#   4. devstat over the committed fixture capture: the device-ledger
#      analyser must accept a known-good capture with its dev
#      sum-check green (vacuously green on a pre-devledger fixture) —
#      the FLOP twin of leg 2.
#   5. check_trace --require-summary over the committed FOLLOW-mode
#      fixture (tests/data/live.fixture.trace.jsonl, a traced
#      --follow --snapshot-chunks run): the live stages
#      (live_poll/live_wait) and the snapshot_published event ride the
#      same schema registry, so a telemetry change that would reject a
#      healthy follow run fails here, not while tailing a sequencer.
#
# tests/test_lint.py runs this script as a tier-1 test, so the gate
# cannot rot out of CI.
set -eu
root="$(cd "$(dirname "$0")/.." && pwd)"
# honour the caller's interpreter (the tier-1 test passes its own
# sys.executable); bare `python` is PATH-dependent on python3-only hosts
py="${PYTHON:-python}"

echo "[ci_check] dutlint --strict (all rules, stale-allowlist fatal)" >&2
mkdir -p "$root/bench_logs"
if ! "$py" "$root/tools/dutlint.py" --strict --json \
        > "$root/bench_logs/dutlint.json"; then
    cat "$root/bench_logs/dutlint.json" >&2
    echo "[ci_check] dutlint --strict failed (report archived to" \
         "bench_logs/dutlint.json)" >&2
    exit 1
fi

echo "[ci_check] dutlint rule count vs README table" >&2
n_rules="$("$py" "$root/tools/dutlint.py" --list-rules | grep -c .)"
n_doc="$(sed -n '/<!-- dutlint-rule-table -->/,/<!-- \/dutlint-rule-table -->/p' \
    "$root/README.md" | grep -c '^| `' || true)"
if [ "$n_rules" != "$n_doc" ]; then
    echo "[ci_check] rule-count drift: dutlint registers $n_rules" \
         "rules but README.md's table documents $n_doc — update the" \
         "table between the dutlint-rule-table markers" >&2
    exit 1
fi

echo "[ci_check] check_trace --require-summary (fixture capture)" >&2
"$py" "$root/tools/check_trace.py" \
    "$root/tests/data/run.fixture.trace.jsonl" --require-summary

echo "[ci_check] fleet_report (2-daemon fixture captures, sum-check)" >&2
"$py" "$root/tools/fleet_report.py" \
    "$root/tests/data/fleet.fixture.a.trace.jsonl" \
    "$root/tests/data/fleet.fixture.b.trace.jsonl" >/dev/null

echo "[ci_check] devstat (fixture capture, dev sum-check)" >&2
"$py" "$root/tools/devstat.py" \
    "$root/tests/data/run.fixture.trace.jsonl" >/dev/null

echo "[ci_check] check_trace --require-summary (live follow fixture)" >&2
"$py" "$root/tools/check_trace.py" \
    "$root/tests/data/live.fixture.trace.jsonl" --require-summary

echo "[ci_check] OK" >&2
