"""Summarise a ``dut-serve`` service capture (kind="service" JSONL).

Run: python tools/serve_report.py SPOOL/service.trace.jsonl [--json]

The service capture records the daemon's whole life: admissions,
per-job slices/preemptions/completions on ``job-<id>`` lanes, service
heartbeats carrying the queue snapshot, and every switchboard event
(fault/retry/durable) that fired while jobs ran. This tool decomposes
it the way ``trace_report.py`` decomposes a run capture:

  * per job: state, slices, preemptions, lease takeovers, fenced
    (zombie) slices, total slice wall, final chunk/consensus counts,
    warm (compile-cache hit) or cold start, and the per-phase busy
    seconds the completing slice reported;
  * service: admission/shed/completion/failure counts, preemption and
    takeover totals, compile-cache hit rate, queue-depth curve
    (max/mean over the heartbeats), retry/fault event counts.

Exit 1 on a capture that fails the service schema
(telemetry/report.validate_service_trace) — a malformed capture must
fail CI the same way a malformed run capture does.
"""

from __future__ import annotations

import argparse
import json
import sys


def summarize(records: list[dict]) -> dict:
    jobs: dict[str, dict] = {}
    hb_depths: list[float] = []
    n_faults = n_retries = 0
    for rec in records:
        if rec.get("type") != "event":
            continue
        name = rec.get("name")
        if name == "heartbeat":
            d = rec.get("queue_depth")
            if isinstance(d, (int, float)):
                hb_depths.append(float(d))
            continue
        if name == "fault_injected":
            n_faults += 1
            continue
        if name == "retry":
            n_retries += 1
            continue
        if not isinstance(name, str) or not (
            name.startswith("job_") or name == "lease_takeover"
        ):
            continue
        job = rec.get("job")
        if not isinstance(job, str):
            continue
        j = jobs.setdefault(
            job,
            {"state": "accepted", "slices": 0, "preemptions": 0,
             "takeovers": 0, "fenced": 0, "watchdogs": 0,
             "wall_s": 0.0, "warm": None},
        )
        if name == "job_accepted":
            j["priority"] = rec.get("priority")
        elif name == "job_rejected":
            j["state"] = "rejected"
            j["error"] = rec.get("reason")
        elif name == "job_shed":
            # admission-control rejection: the job never entered the
            # queue — its reason is the shed policy's verdict
            j["state"] = "shed"
            j["error"] = rec.get("reason")
            j["priority"] = rec.get("priority", j.get("priority"))
        elif name == "lease_takeover":
            j["takeovers"] += 1
            j["takeover_reason"] = rec.get("reason")
        elif name == "job_fenced":
            j["fenced"] += 1
        elif name == "job_started":
            j["slices"] += 1
            if j["warm"] is None:
                j["warm"] = bool(rec.get("warm"))
            # shard lineage (serve/shard/): sub-job slices carry their
            # parent id + shard index; parent slices carry the stage
            if isinstance(rec.get("parent"), str):
                j["parent"] = rec["parent"]
                j["shard_idx"] = rec.get("shard_idx")
        elif name == "job_split":
            # the parent fanned out: sub-jobs registered, merge pending
            j["state"] = "fanned"
            j["n_shards"] = rec.get("n_shards")
            j["n_plan_chunks"] = rec.get("n_chunks")
        elif name == "job_merged":
            j["n_shards"] = rec.get("n_shards", j.get("n_shards"))
            j["merge_s"] = rec.get("merge_s")
            j["merged_bytes"] = rec.get("output_bytes")
        elif name == "job_preempted":
            j["preemptions"] += 1
            j["wall_s"] = round(j["wall_s"] + float(rec.get("wall_s") or 0), 3)
            j["chunks_done"] = rec.get("chunks_done")
        elif name == "job_completed":
            j["state"] = "done"
            j["wall_s"] = round(j["wall_s"] + float(rec.get("wall_s") or 0), 3)
            j["n_chunks"] = rec.get("n_chunks")
            j["n_consensus"] = rec.get("n_consensus")
            sec = rec.get("seconds")
            if isinstance(sec, dict):
                j["seconds"] = sec
            # whole-job byte ledger (all slices), from the service's
            # per-job accumulation — pre-ledger captures simply lack
            # it; device_flops/mfu are the device-ledger twin
            for key in ("h2d_bytes", "d2h_bytes", "bytes_per_read",
                        "device_flops", "mfu"):
                if isinstance(rec.get(key), (int, float)):
                    j[key] = rec[key]
        elif name == "job_failed":
            j["state"] = "failed"
            j["error"] = rec.get("error")
        elif name == "job_expired":
            # deadline verdict: terminal, with the durable reason
            j["state"] = "expired"
            j["error"] = rec.get("reason")
        elif name == "job_quarantined":
            # poison verdict: terminal after crash_count unclean aborts
            j["state"] = "quarantined"
            j["error"] = rec.get("reason")
            j["crash_count"] = rec.get("crash_count")
        elif name == "watchdog_fired":
            j["watchdogs"] += 1
            j["stalled_s"] = rec.get("stalled_s")
    last = records[-1] if records else {}
    summary = last if isinstance(last, dict) and last.get("type") == "summary" else {}
    counters = summary.get("counters") if isinstance(summary, dict) else None
    done = sum(1 for j in jobs.values() if j["state"] == "done")
    failed = sum(1 for j in jobs.values() if j["state"] == "failed")
    warm_known = [j for j in jobs.values() if j["warm"] is not None]
    out = {
        "n_jobs": len(jobs),
        "n_done": done,
        "n_failed": failed,
        "n_rejected": sum(1 for j in jobs.values() if j["state"] == "rejected"),
        "n_shed": sum(1 for j in jobs.values() if j["state"] == "shed"),
        # disk-pressure sheds carry a "shed: disk ..." reason — split
        # out so overload-by-disk is legible apart from class/queue
        # bounds
        "n_disk_shed": sum(
            1 for j in jobs.values()
            if j["state"] == "shed"
            and str(j.get("error", "")).startswith("shed: disk")
        ),
        "n_expired": sum(1 for j in jobs.values() if j["state"] == "expired"),
        "n_quarantined": sum(
            1 for j in jobs.values() if j["state"] == "quarantined"
        ),
        "n_watchdog_fired": sum(j["watchdogs"] for j in jobs.values()),
        "n_takeovers": sum(j["takeovers"] for j in jobs.values()),
        "n_fenced": sum(j["fenced"] for j in jobs.values()),
        "n_preemptions": sum(j["preemptions"] for j in jobs.values()),
        "n_warm_starts": sum(1 for j in warm_known if j["warm"]),
        "n_cold_starts": sum(1 for j in warm_known if not j["warm"]),
        "n_fault_events": n_faults,
        "n_retry_events": n_retries,
        "queue_depth_max": max(hb_depths) if hb_depths else 0,
        "queue_depth_mean": (
            round(sum(hb_depths) / len(hb_depths), 2) if hb_depths else 0
        ),
        "clean_shutdown": bool(summary),
        "jobs": jobs,
    }
    # scatter-gather rollup: every job that fanned out (or that shard
    # sub-jobs point at) gets a parent row aggregating its shards
    parents: dict[str, dict] = {}
    for job_id, j in jobs.items():
        if "n_shards" in j:
            parents.setdefault(job_id, {}).update({
                "n_shards": j.get("n_shards"),
                "state": j["state"],
                "merge_s": j.get("merge_s"),
            })
    for job_id, j in jobs.items():
        parent = j.get("parent")
        if not isinstance(parent, str):
            continue
        p = parents.setdefault(parent, {})
        p["n_shard_jobs"] = p.get("n_shard_jobs", 0) + 1
        p.setdefault("shard_states", {})
        p["shard_states"][j["state"]] = (
            p["shard_states"].get(j["state"], 0) + 1
        )
    if parents:
        out["parents"] = parents
        out["n_split"] = len(parents)
        out["n_merged"] = sum(
            1 for p in parents.values() if p.get("merge_s") is not None
        )
    if isinstance(counters, dict):
        out["service_counters"] = counters
    return out


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="serve_report.py",
        description="summarise a dut-serve service telemetry capture",
    )
    ap.add_argument("trace", help="kind=\"service\" JSONL capture")
    ap.add_argument("--json", action="store_true", help="machine-readable")
    args = ap.parse_args(argv)

    from duplexumiconsensusreads_tpu.telemetry import report

    try:
        records = report.load_trace(args.trace)
    except (OSError, ValueError) as e:
        print(f"serve_report: {e}", file=sys.stderr)
        return 1
    problems = report.validate_service_trace(records)
    if problems:
        for p in problems:
            print(f"serve_report: {args.trace}: {p}", file=sys.stderr)
        return 1
    s = summarize(records)
    if args.json:
        print(json.dumps(s, sort_keys=True))
        return 0
    print(
        f"service: {s['n_jobs']} jobs ({s['n_done']} done, "
        f"{s['n_failed']} failed, {s['n_rejected']} rejected, "
        f"{s['n_shed']} shed), "
        f"{s['n_preemptions']} preemptions, "
        f"{s['n_warm_starts']}/{s['n_warm_starts'] + s['n_cold_starts']} "
        f"warm starts"
        + ("" if s["clean_shutdown"] else
           "  [no summary record: daemon did not shut down cleanly]")
    )
    if s["n_takeovers"] or s["n_fenced"]:
        print(
            f"fleet: {s['n_takeovers']} lease takeovers, "
            f"{s['n_fenced']} fenced (zombie) slices"
        )
    if (
        s["n_expired"] or s["n_quarantined"] or s["n_watchdog_fired"]
        or s["n_disk_shed"]
    ):
        print(
            f"defense: {s['n_expired']} expired, "
            f"{s['n_quarantined']} quarantined, "
            f"{s['n_watchdog_fired']} watchdog fires, "
            f"{s['n_disk_shed']} disk sheds"
        )
    if s["queue_depth_max"]:
        print(
            f"queue depth over heartbeats: max {s['queue_depth_max']:.0f} "
            f"mean {s['queue_depth_mean']}"
        )
    if s["n_fault_events"] or s["n_retry_events"]:
        print(
            f"switchboard: {s['n_fault_events']} injected faults, "
            f"{s['n_retry_events']} retries"
        )
    if s.get("parents"):
        # scatter-gather rollup: one line per parent, shard states
        # aggregated — the fleet-wide progress view of a sharded job
        print(f"sharding: {s['n_split']} parents fanned out, "
              f"{s['n_merged']} merged")
        for pid in sorted(s["parents"]):
            p = s["parents"][pid]
            states = ", ".join(
                f"{n} {st}" for st, n in
                sorted(p.get("shard_states", {}).items())
            ) or "no shard slices in capture"
            merge = (
                f", merge {p['merge_s']:.3f}s"
                if isinstance(p.get("merge_s"), (int, float)) else ""
            )
            print(f"  {pid}: {p.get('n_shards', '?')} shards "
                  f"({states}){merge}")
    print(f"{'job':<18} {'state':<11} {'pri':>3} {'slices':>6} "
          f"{'preempt':>7} {'wd':>3} {'wall_s':>8} {'warm':>5} "
          f"{'h2d_mb':>8} {'d2h_mb':>8} {'B/read':>7} {'mfu':>7} "
          f"{'lineage':>12}")
    def _mb(v):
        return f"{v / 1e6:.1f}" if isinstance(v, (int, float)) else "-"

    def _fmt_mfu(v):
        # "-" for pre-devledger captures (no mfu on the event at all)
        return f"{v:.2g}" if isinstance(v, (int, float)) else "-"

    for job_id in sorted(s["jobs"]):
        j = s["jobs"][job_id]
        bpr = j.get("bytes_per_read")
        if isinstance(j.get("parent"), str):
            lineage = f"{j['parent'][-8:]}#{j.get('shard_idx')}"
        elif "n_shards" in j:
            lineage = f"parent/{j.get('n_shards')}"
        else:
            lineage = "-"
        print(
            f"{job_id:<18} {j['state']:<11} {str(j.get('priority', '?')):>3} "
            f"{j['slices']:>6} {j['preemptions']:>7} "
            f"{j.get('watchdogs', 0):>3} {j['wall_s']:>8.3f} "
            f"{str(j['warm']):>5} {_mb(j.get('h2d_bytes')):>8} "
            f"{_mb(j.get('d2h_bytes')):>8} "
            f"{f'{bpr:g}' if isinstance(bpr, (int, float)) else '-':>7} "
            f"{_fmt_mfu(j.get('mfu')):>7} "
            f"{lineage:>12}"
        )
        sec = j.get("seconds")
        if isinstance(sec, dict):
            busy = {k: v for k, v in sorted(sec.items())
                    if k not in ("total", "drain_utilization") and v}
            if busy:
                print(f"{'':<18}   " + " ".join(
                    f"{k}={v:.3g}" for k, v in busy.items()
                ))
    return 0


if __name__ == "__main__":
    import os as _os

    sys.path.insert(0, _os.path.dirname(_os.path.dirname(_os.path.abspath(__file__))))
    raise SystemExit(main())
